"""MNIST training with horovod_tpu — the JAX-native mirror of the
reference's examples/pytorch/pytorch_mnist.py / tensorflow2_mnist.py:

1. ``hvd.init()``
2. shard the dataset per process (``hvd.shard_id()/num_shards()``)
3. wrap the optimizer with ``hvd.DistributedOptimizer``
4. broadcast initial parameters from rank 0
5. train; only rank 0 logs/checkpoints

Uses synthetic MNIST-shaped data when no dataset is available (zero-egress
environments); pass --data-dir with an npz of (x_train, y_train) to use
real data.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistConvNet
from horovod_tpu.parallel import data_parallel_step, shard_batch


def load_data(data_dir):
    if data_dir:
        d = np.load(f"{data_dir}/mnist.npz")
        return d["x_train"].astype(np.float32)[..., None] / 255.0, d["y_train"]
    rng = np.random.RandomState(0)
    x = rng.rand(4096, 28, 28, 1).astype(np.float32)
    y = (x.sum((1, 2, 3)) * 7).astype(np.int32) % 10  # learnable synthetic rule
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64, help="per-chip batch")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--data-dir", default="")
    args = p.parse_args()

    hvd.init()
    x, y = load_data(args.data_dir)
    # per-process dataset shard (reference: torch DistributedSampler usage)
    x = x[hvd.shard_id()::hvd.num_shards()]
    y = y[hvd.shard_id()::hvd.num_shards()]

    model = MnistConvNet()
    params = model.init(jax.random.PRNGKey(42), jnp.zeros((1, 28, 28, 1)))["params"]
    # scale LR by world size (Horovod convention, docs/concepts)
    opt = hvd.DistributedOptimizer(optax.adam(args.lr * hvd.size()))
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images)
            onehot = jax.nn.one_hot(labels, 10)
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
            acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
            return loss, acc
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, "hvd"), jax.lax.pmean(acc, "hvd")

    compiled = data_parallel_step(step, batch_argnums=(2, 3), donate_argnums=(0, 1))

    global_batch = args.batch_size * hvd.size() // hvd.num_shards()
    steps_per_epoch = len(x) // global_batch
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        perm = np.random.RandomState(epoch).permutation(len(x))
        for i in range(steps_per_epoch):
            idx = perm[i * global_batch:(i + 1) * global_batch]
            params, opt_state, loss, acc = compiled(
                params, opt_state, jnp.asarray(x[idx]), jnp.asarray(y[idx]))
        if hvd.rank() == 0:
            dt = time.perf_counter() - t0
            print(f"epoch {epoch}: loss={float(loss):.4f} acc={float(acc):.3f} "
                  f"({steps_per_epoch * global_batch / dt:.0f} img/s)")


if __name__ == "__main__":
    main()
