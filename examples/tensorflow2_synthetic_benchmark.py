"""TF2 synthetic benchmark over the eager shim (reference
examples/tensorflow2/tensorflow2_synthetic_benchmark.py shape: synthetic
batches, DistributedGradientTape, img/sec per worker + total).

Run:  hvdrun -np 2 python examples/tensorflow2_synthetic_benchmark.py
"""

import argparse
import time

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(32, 3, strides=2, padding="same",
                               activation="relu"),
        tf.keras.layers.Conv2D(64, 3, strides=2, padding="same",
                               activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(10),
    ])
    opt = tf.keras.optimizers.SGD(0.01 * hvd.cross_size())
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    data = tf.random.uniform([args.batch_size, 64, 64, 3])
    target = tf.random.uniform([args.batch_size], maxval=10, dtype=tf.int64)

    first = {"done": False}

    def benchmark_step():
        with tf.GradientTape() as tape:
            loss = loss_fn(target, model(data, training=True))
        tape = hvd.DistributedGradientTape(tape, compression=compression)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if not first["done"]:
            # both model weights AND optimizer slots: stateful optimizers
            # (momentum/Adam) would otherwise keep per-worker slot values
            # seeded from divergent pre-broadcast gradients
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
            first["done"] = True

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.time()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        img_secs.append(args.batch_size * args.num_batches_per_iter
                        / (time.time() - t0))

    img_sec_mean, img_sec_conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        n = hvd.cross_size()
        print(f"Img/sec per worker: {img_sec_mean:.1f} +- {img_sec_conf:.1f}")
        print(f"Total img/sec on {n} worker(s): "
              f"{n * img_sec_mean:.1f} +- {n * img_sec_conf:.1f}")


if __name__ == "__main__":
    main()
