"""Elastic training example (reference examples/elastic/ usage shape:
``@hvd.elastic.run`` + a State object; workers can join/leave and training
resumes from the last committed state).

Run under the elastic launcher:
    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh python examples/elastic_jax.py
or single-process (degenerates to a plain loop):
    python examples/elastic_jax.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
import horovod_tpu.elastic as elastic
from horovod_tpu.models import MLP
from horovod_tpu.parallel import data_parallel_step, shard_batch


def make_data(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 16).astype(np.float32)
    W = rng.randn(16, 1).astype(np.float32)
    y = (X @ W).astype(np.float32)
    return X, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    hvd.init()
    X, y = make_data()
    model = MLP(features=[64, 1])
    params = model.init(jax.random.PRNGKey(0), X[:1])
    opt = optax.adam(1e-2 * hvd.size())  # LR scales with current world size
    opt_state = opt.init(params)

    state = elastic.JaxState(params=params, opt_state=opt_state, epoch=0)

    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            return jnp.mean((model.apply(p, xb) - yb) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(
            lambda g: hvd.allreduce(g, axis_name="hvd"), grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            jax.lax.pmean(loss, "hvd")

    @elastic.run
    def train(state):
        compiled = data_parallel_step(step, batch_argnums=(2, 3))
        n = hvd.size()
        per = (len(X) // max(n, 1)) // args.batch * args.batch
        while state.epoch < args.epochs:
            # rank-strided shard of the data for the *current* world size
            Xl = X[hvd.rank()::n][:per]
            yl = y[hvd.rank()::n][:per]
            loss = None
            for i in range(0, per, args.batch):
                xb, yb = shard_batch((Xl[i:i + args.batch],
                                      yl[i:i + args.batch]))
                state.params, state.opt_state, loss = compiled(
                    state.params, state.opt_state, xb, yb)
            state.epoch += 1
            state.commit()  # snapshot + membership check
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={float(loss):.5f} "
                      f"(world size {n})")

    train(state)
    if hvd.rank() == 0:
        print("elastic training complete")


if __name__ == "__main__":
    main()
