"""Synthetic throughput benchmark — mirror of the reference's
examples/tensorflow2/tensorflow2_synthetic_benchmark.py (same flags,
same output format: "Img/sec per device" + total), on JAX/TPU.

Example:
    python examples/jax_synthetic_benchmark.py --model ResNet50 --batch-size 64
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models
from horovod_tpu.parallel import data_parallel_step


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50")
    p.add_argument("--batch-size", type=int, default=64, help="per-chip")
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--use-adasum", action="store_true")
    args = p.parse_args()

    hvd.init()
    model = getattr(models, args.model)(num_classes=1000, dtype=jnp.bfloat16)
    n = hvd.size()
    batch = args.batch_size * n
    images = jnp.asarray(np.random.RandomState(0).randn(batch, 224, 224, 3),
                         jnp.bfloat16)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 1000, (batch,)))

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    compression = hvd.Compression.fp16 if args.fp16_allreduce else hvd.Compression.none
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average)
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def step(state, opt_state, images, labels):
        params, batch_stats = state

        def loss_fn(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": batch_stats}, images, train=True,
                mutable=["batch_stats"])
            onehot = jax.nn.one_hot(labels, 1000)
            return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)), upd
        (loss, upd), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return ((optax.apply_updates(params, updates), upd["batch_stats"]),
                opt_state, jax.lax.pmean(loss, "hvd"))

    compiled = data_parallel_step(step, batch_argnums=(2, 3))
    state = (params, batch_stats)

    if hvd.rank() == 0:
        print(f"Model: {args.model}, Batch size: {args.batch_size} per chip, "
              f"Number of chips: {n}")
    for _ in range(args.num_warmup_batches):
        state, opt_state, loss = compiled(state, opt_state, images, labels)
    jax.block_until_ready(loss)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, opt_state, loss = compiled(state, opt_state, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        rate = batch * args.num_batches_per_iter / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate / n:.1f} img/sec per chip")
    if hvd.rank() == 0:
        mean = np.mean(img_secs)
        print(f"Img/sec per chip: {mean / n:.1f} +-{1.96 * np.std(img_secs) / n:.1f}")
        print(f"Total img/sec on {n} chip(s): {mean:.1f}")


if __name__ == "__main__":
    main()
