"""Synthetic throughput benchmark — mirror of the reference's
examples/tensorflow2/tensorflow2_synthetic_benchmark.py (same flags,
same output format: "Img/sec per device" + total), on JAX/TPU.

Example:
    python examples/jax_synthetic_benchmark.py --model ResNet50 --batch-size 64
    python examples/jax_synthetic_benchmark.py --model InceptionV3 --image-size 299
    python examples/jax_synthetic_benchmark.py --model VGG16

Any registered model family works (ResNet50/101/152, InceptionV3,
VGG16/19, ViT_*): models without batch norm or with dropout are handled
uniformly.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models
from horovod_tpu.parallel import data_parallel_step


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50")
    p.add_argument("--image-size", type=int, default=224,
                   help="input resolution (299 is InceptionV3's canonical)")
    p.add_argument("--batch-size", type=int, default=64, help="per-chip")
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--use-adasum", action="store_true")
    args = p.parse_args()

    hvd.init()
    model = getattr(models, args.model)(num_classes=1000, dtype=jnp.bfloat16)
    n = hvd.size()
    batch = args.batch_size * n
    sz = args.image_size
    images = jnp.asarray(np.random.RandomState(0).randn(batch, sz, sz, 3),
                         jnp.bfloat16)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 1000, (batch,)))

    # extra rngs are ignored by models that take none (flax contract), so
    # one init/apply shape serves BN-only, dropout-only, and plain models
    rngs = {"params": jax.random.PRNGKey(0),
            "dropout": jax.random.PRNGKey(17)}
    variables = model.init(rngs, images[:2], train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    compression = hvd.Compression.fp16 if args.fp16_allreduce else hvd.Compression.none
    opt = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average)
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def step(state, opt_state, images, labels):
        params, batch_stats, rng_step = state
        rng_step, drop_key = jax.random.split(rng_step)

        def loss_fn(p):
            v = {"params": p}
            if batch_stats is not None:
                v["batch_stats"] = batch_stats
                logits, upd = model.apply(
                    v, images, train=True, mutable=["batch_stats"],
                    rngs={"dropout": drop_key})
                new_stats = upd["batch_stats"]
            else:
                logits = model.apply(v, images, train=True,
                                     rngs={"dropout": drop_key})
                new_stats = None
            onehot = jax.nn.one_hot(labels, 1000)
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
            return loss, new_stats
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return ((optax.apply_updates(params, updates), new_stats, rng_step),
                opt_state, jax.lax.pmean(loss, "hvd"))

    compiled = data_parallel_step(step, batch_argnums=(2, 3))
    # a fresh dropout key every step (folded through the carried state)
    state = (params, batch_stats, jax.random.PRNGKey(42))

    if hvd.rank() == 0:
        print(f"Model: {args.model}, Batch size: {args.batch_size} per chip, "
              f"Number of chips: {n}")
    for _ in range(args.num_warmup_batches):
        state, opt_state, loss = compiled(state, opt_state, images, labels)
    jax.block_until_ready(loss)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, opt_state, loss = compiled(state, opt_state, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        rate = batch * args.num_batches_per_iter / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate / n:.1f} img/sec per chip")
    if hvd.rank() == 0:
        mean = np.mean(img_secs)
        print(f"Img/sec per chip: {mean / n:.1f} +-{1.96 * np.std(img_secs) / n:.1f}")
        print(f"Total img/sec on {n} chip(s): {mean:.1f}")


if __name__ == "__main__":
    main()
