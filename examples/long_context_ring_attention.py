"""Long-context training with ring attention (sequence parallelism).

Greenfield vs the reference (SURVEY.md §5.7: reference Horovod has no
long-context machinery): shard a sequence far longer than one chip's
attention memory across the mesh 'sp' axis and train a causal
transformer block end to end, K/V blocks rotating over ICI via
`horovod_tpu.parallel.ring_attention`.

Memory math: full causal attention materializes O(s²) scores — at
s=32768, bf16, 8 heads that is ~16 GiB per layer, beyond one v5e chip.
Ring attention holds one (s_loc × s_loc) block per step, s_loc = s/n.

Example:
    python examples/long_context_ring_attention.py --seq-len 8192
    hvdrun -np 2 python examples/long_context_ring_attention.py
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import (ring_attention, stripe_tokens,
                                  striped_ring_attention)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=8192,
                   help="global sequence length (sharded over 'sp')")
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--striped", action="store_true",
                   help="striped token layout: equal triangular work on "
                        "every chip each round (~2x utilization for "
                        "causal; docs/parallelism.md)")
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    if args.seq_len % n:
        raise SystemExit(f"--seq-len must divide by {n} chips")
    mesh = jax.sharding.Mesh(
        np.array(hvd.global_process_set().devices), ("sp",))
    hd = args.d_model // args.heads

    rng = np.random.RandomState(0)
    params = {
        "wq": jnp.asarray(rng.randn(args.d_model, args.d_model) * 0.02,
                          jnp.float32),
        "wk": jnp.asarray(rng.randn(args.d_model, args.d_model) * 0.02,
                          jnp.float32),
        "wv": jnp.asarray(rng.randn(args.d_model, args.d_model) * 0.02,
                          jnp.float32),
        "wo": jnp.asarray(rng.randn(args.d_model, args.d_model) * 0.02,
                          jnp.float32),
    }
    x = jnp.asarray(rng.randn(args.batch_size, args.seq_len, args.d_model),
                    jnp.float32)
    if args.striped:
        # chip i holds tokens i, i+n, 2n+i, ... (synthetic objective, so
        # the shifted-target loss stays a valid regression either way)
        x = stripe_tokens(x, n)
    opt = optax.adam(1e-4)
    opt_state = opt.init(params)

    def block(params, x_loc):
        """One attention block on this chip's sequence shard."""
        b, s_loc, _ = x_loc.shape

        def heads(w):
            return (x_loc @ w).reshape(b, s_loc, args.heads, hd)

        attn = striped_ring_attention if args.striped else ring_attention
        out = attn(heads(params["wq"]) / np.sqrt(hd),
                   heads(params["wk"]), heads(params["wv"]),
                   axis_name="sp")
        return out.reshape(b, s_loc, args.d_model) @ params["wo"]

    def local_step(params, opt_state, x_loc):
        def loss_fn(p):
            y = block(p, x_loc)
            # toy objective: predict the input's next token embedding
            return jnp.mean((y[:, :-1] - x_loc[:, 1:]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # Horovod semantics: per-shard local grads + explicit allreduce —
        # replicated params must see identical updates on every chip
        grads = jax.lax.pmean(grads, "sp")
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            jax.lax.pmean(loss, "sp")

    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P(None, "sp", None)),
        out_specs=(P(), P(), P()), check_vma=False))

    params_, opt_state_, loss = step(params, opt_state, x)
    jax.block_until_ready(loss)  # compile + step 0
    t0 = time.perf_counter()
    for _ in range(args.steps):
        params_, opt_state_, loss = step(params_, opt_state_, x)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    tok_s = args.batch_size * args.seq_len / dt
    if hvd.rank() == 0:
        layout = "striped" if args.striped else "blocked"
        print(f"seq={args.seq_len} over {n} chips [{layout}] "
              f"(s_loc={args.seq_len // n}): "
              f"{dt * 1e3:.1f} ms/step, {tok_s:,.0f} tok/s, "
              f"final loss {float(loss):.5f}")


if __name__ == "__main__":
    main()
