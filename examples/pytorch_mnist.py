"""PyTorch MNIST with horovod_tpu.torch — mirrors the reference's
examples/pytorch/pytorch_mnist.py structure (BASELINE.md tracked config 1):
DistributedOptimizer + broadcast_parameters/optimizer_state, per-rank data
sharding, rank-0 logging. Synthetic data in zero-egress environments."""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--use-adasum", action="store_true")
    p.add_argument("--fp16-allreduce", action="store_true")
    # (sampler-based loading also demonstrates hvd.ElasticSampler)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)

    rng = np.random.RandomState(0)
    x = torch.tensor(rng.rand(2048, 1, 28, 28), dtype=torch.float32)
    y = torch.tensor((rng.rand(2048) * 10), dtype=torch.long) % 10
    # per-process sharding via ElasticSampler (reference ElasticSampler /
    # DistributedSampler). The record_batch tracking becomes load-bearing
    # when the sampler is registered with hvd.elastic TorchState(sampler=)
    # in an elastic run; here it demonstrates the API
    dataset = torch.utils.data.TensorDataset(x, y)
    sampler = hvd.ElasticSampler(dataset, shuffle=True)
    loader = torch.utils.data.DataLoader(
        dataset, batch_size=args.batch_size, sampler=sampler)

    model = Net()
    # linear LR scaling by the number of gradient contributors: the eager
    # torch path averages per *process* (cross_size), not per chip
    lr_scaler = hvd.cross_size() if not args.use_adasum else 1
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr * lr_scaler,
                                momentum=0.5)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    for epoch in range(args.epochs):
        model.train()
        sampler.set_epoch(epoch)
        loss = None
        for batch_idx, (bx, by) in enumerate(loader):
            optimizer.zero_grad()
            loss = F.nll_loss(model(bx), by)
            loss.backward()
            optimizer.step()
            sampler.record_batch(batch_idx, args.batch_size)
        if hvd.rank() == 0 and loss is not None:
            print(f"epoch {epoch}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
