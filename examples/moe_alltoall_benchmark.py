"""MoE expert-parallel alltoall exchange benchmark — BASELINE.md tracked
config 5 ("hvd.alltoall + hvd.allgather for MoE/expert-parallel gradient
exchange"; reference primitive: operations.cc:1131-1193 alltoall).

Measures (a) the full expert-parallel MoE layer step and (b) the raw
eager hvd.alltoall / hvd.allgather exchange bandwidth.

Run: python examples/moe_alltoall_benchmark.py        (all local chips)
     hvdrun -np 2 python examples/moe_alltoall_benchmark.py
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.parallel import create_mesh
from horovod_tpu.parallel.moe import moe_layer
from jax.sharding import PartitionSpec as P


def bench_moe_layer(tokens_per_chip: int, d_model: int, n_experts: int,
                    iters: int = 20):
    n = len(jax.devices())
    mesh = create_mesh({"ep": n})
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(tokens_per_chip * n, d_model), jnp.bfloat16)
    gate_w = jnp.asarray(rng.randn(d_model, n_experts), jnp.float32)
    e_local = n_experts // n
    w1 = jnp.asarray(rng.randn(n_experts, d_model, 4 * d_model) * 0.02,
                     jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(n_experts, 4 * d_model, d_model) * 0.02,
                     jnp.bfloat16)

    def expert_fn(params, xe):
        a, b = params
        return jax.nn.gelu(xe @ a) @ b

    def step(x, gate_w, w1, w2):
        def per_chip(xl, gw, w1l, w2l):
            y, aux = moe_layer(xl, gw, expert_fn, (w1l, w2l),
                               axis_name="ep")
            return y

        return jax.shard_map(
            per_chip, mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep")),
            out_specs=P("ep"), check_vma=False)(x, gate_w, w1, w2)

    compiled = jax.jit(step)
    y = compiled(x, gate_w, w1, w2)
    jax.block_until_ready(y)
    t0 = time.perf_counter()
    for _ in range(iters):
        y = compiled(x, gate_w, w1, w2)
    float(jnp.sum(y))  # value fetch = true sync
    dt = (time.perf_counter() - t0) / iters
    toks = tokens_per_chip * n
    print(f"moe_layer: {toks / dt:,.0f} tokens/s  ({dt * 1e3:.2f} ms/step, "
          f"{n} chips, {n_experts} experts)")
    return toks / dt


def bench_eager_exchange(nbytes: int, iters: int = 10):
    """Raw eager alltoall + allgather bandwidth (the BASELINE metric)."""
    n = hvd.size()
    elems = nbytes // 4
    x = np.random.RandomState(1).randn(elems).astype(np.float32)
    for name, fn in (
        ("alltoall", lambda i: hvd.alltoall(x, name=f"bench.a2a.{i}")),
        ("allgather", lambda i: hvd.allgather(x, name=f"bench.ag.{i}")),
    ):
        fn(0)  # warm the compiled program
        t0 = time.perf_counter()
        for i in range(1, iters + 1):
            out = fn(i)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        gbps = nbytes / dt / 1e9
        print(f"eager {name}: {gbps:.2f} GB/s ({nbytes / 1e6:.0f} MB, "
              f"{n} procs)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens-per-chip", type=int, default=4096)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--exchange-mb", type=int, default=64)
    args = ap.parse_args()

    hvd.init()
    n_experts = max(args.experts, len(jax.devices()))
    bench_moe_layer(args.tokens_per_chip, args.d_model, n_experts)
    bench_eager_exchange(args.exchange_mb << 20)


if __name__ == "__main__":
    main()
