"""Adasum training example — BASELINE.md tracked config 4 (reference
examples/adasum + docs/adasum_user_guide.rst usage shape): gradients are
combined with the scale-invariant Adasum reduction over the ICI mesh
instead of an average.

Run single-chip:   python examples/adasum_jax.py
Run multi-process: hvdrun -np 2 python examples/adasum_jax.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MLP
from horovod_tpu.parallel import data_parallel_step, shard_batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()

    rng = np.random.RandomState(0)
    X = rng.randn(args.batch * max(n, 1), 16).astype(np.float32)
    W = rng.randn(16, 1).astype(np.float32)
    y = (X @ W + 0.1 * rng.randn(len(X), 1)).astype(np.float32)

    model = MLP(features=[64, 64, 1])
    params = model.init(jax.random.PRNGKey(0), X[:1])
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = optax.sgd(0.01)
    opt_state = opt.init(params)

    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            pred = model.apply(p, xb)
            return jnp.mean((pred - yb) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # the Horovod Adasum reduction (reference ReduceOp.ADASUM /
        # adasum.h recursion) — here the ppermute hypercube over ICI
        grads = jax.tree.map(
            lambda g: hvd.allreduce(g, op=hvd.Adasum, axis_name="hvd"),
            grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, \
            jax.lax.pmean(loss, "hvd")

    compiled = data_parallel_step(step, batch_argnums=(2, 3))
    xb, yb = shard_batch((X, y))
    for i in range(args.steps):
        params, opt_state, loss = compiled(params, opt_state, xb, yb)
        if i % 10 == 0 and hvd.rank() == 0:
            print(f"step {i}: loss={float(loss):.5f}")
    if hvd.rank() == 0:
        print(f"final loss={float(loss):.5f}")


if __name__ == "__main__":
    main()
