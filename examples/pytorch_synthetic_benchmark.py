"""PyTorch synthetic benchmark over the eager shim (reference
examples/pytorch/pytorch_synthetic_benchmark.py shape: synthetic batches,
DistributedOptimizer, img/sec per worker + total with stddev).

Run:  hvdrun -np 2 python examples/pytorch_synthetic_benchmark.py
"""

import argparse
import time

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(1234)

    # small conv net standing in for the reference's torchvision model
    # (no torchvision download in zero-egress environments)
    model = torch.nn.Sequential(
        torch.nn.Conv2d(3, 32, 3, stride=2, padding=1), torch.nn.ReLU(),
        torch.nn.Conv2d(32, 64, 3, stride=2, padding=1), torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1), torch.nn.Flatten(),
        torch.nn.Linear(64, 10))
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.cross_size())
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, 64, 64)
    target = torch.randint(0, 10, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.time()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        img_sec = args.batch_size * args.num_batches_per_iter / (time.time() - t0)
        img_secs.append(img_sec)

    img_sec_mean, img_sec_conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        n = hvd.cross_size()
        print(f"Img/sec per worker: {img_sec_mean:.1f} +- {img_sec_conf:.1f}")
        print(f"Total img/sec on {n} worker(s): "
              f"{n * img_sec_mean:.1f} +- {n * img_sec_conf:.1f}")


if __name__ == "__main__":
    main()
