"""Ray-executor training launch (reference examples/ray/ray_train.py usage
shape: build a RayExecutor, run a training function on every worker).

Works without a Ray cluster: the executor falls back to the hermetic
local-process engine, which exercises identical placement/topology/env
logic. With ray installed and `ray.init()` done first, the same script
drives real Ray actors.

Run:  python examples/ray_run.py --workers 2
"""

import argparse


def train_fn(steps: int):
    """Runs on every worker with HOROVOD_* env set by the executor."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.RandomState(1234)  # same data everywhere: size-1 demo
    w = np.zeros(4, np.float32)
    for step in range(steps):
        x = rng.randn(32, 4).astype(np.float32)
        g = x.mean(axis=0)  # stand-in gradient
        h = hvd.allreduce_async(g, average=True, name=f"ray.g.{step}")
        w -= 0.1 * np.asarray(hvd.synchronize(h))
    return float(np.linalg.norm(w)), hvd.rank()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--elastic", action="store_true")
    args = ap.parse_args()

    if args.elastic:
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.ray import ElasticRayExecutor

        settings = ElasticRayExecutor.create_settings(
            min_np=args.workers, max_np=args.workers)
        ex = ElasticRayExecutor(
            settings, discovery=FixedHosts({"localhost": args.workers}))
        ex.start()
        results = ex.run(train_fn, args=(args.steps,))
        ex.shutdown()
    else:
        from horovod_tpu.ray import RayExecutor

        ex = RayExecutor(num_workers=args.workers)
        ex.start()
        results = ex.run(train_fn, args=(args.steps,))
        ex.shutdown()
    for norm, rank in results:
        print(f"rank {rank}: |w| = {norm:.4f}")


if __name__ == "__main__":
    main()
