"""Spark-style estimator training (reference examples/spark/pytorch/
pytorch_spark_mnist.py usage shape: build an estimator around a model +
store, fit a DataFrame, transform predictions).

Runs hermetically on a pandas DataFrame (no Spark needed); with pyspark
installed the same estimator accepts a Spark DataFrame and
``horovod_tpu.spark.run`` launches one worker per executor.

Run:  python examples/spark_estimator.py
"""

import tempfile

import numpy as np
import pandas as pd
import torch

from horovod_tpu.spark import FilesystemStore, TorchEstimator


def main():
    torch.manual_seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(512, 8).astype(np.float32)
    w = rng.randn(8, 1).astype(np.float32)
    y = (x @ w + 0.01 * rng.randn(512, 1)).astype(np.float32)
    df = pd.DataFrame({"features": list(x), "label": list(y[:, 0])})

    store = FilesystemStore(tempfile.mkdtemp(prefix="hvd_spark_store_"))
    est = TorchEstimator(
        model=torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.ReLU(),
                                  torch.nn.Linear(16, 1)),
        optimizer=lambda p: torch.optim.Adam(p, lr=0.01),
        loss=torch.nn.MSELoss(),
        feature_cols=["features"], label_cols=["label"],
        validation=0.1, batch_size=64, epochs=20,
        store=store, run_id="spark_example", verbose=0)
    model = est.fit(df)
    out = model.transform(df)
    pred = np.asarray(list(out["prediction"]), np.float32)
    print(f"train MSE: {float(np.mean((pred - y[:, 0]) ** 2)):.5f}")
    print(f"checkpoint at: {est.checkpoint_path()}")


if __name__ == "__main__":
    main()
