"""Keras MNIST with the wrapped optimizer + Horovod callbacks
(reference examples/keras/keras_mnist.py usage shape: init → scale LR by
size → DistributedOptimizer → broadcast + metric-average + LR-warmup
callbacks → rank-0-only checkpoint).

Run:  hvdrun -np 2 python examples/keras_mnist.py --epochs 2
"""

import argparse

import numpy as np

import horovod_tpu.keras as hvd


def synthetic_mnist(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int64)
    for i in range(n):
        q = y[i] % 4
        r, c = divmod(q, 2)
        x[i, r * 14:(r + 1) * 14, c * 14:(c + 1) * 14] += y[i] / 10.0
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    import keras

    hvd.init()

    x, y = synthetic_mnist()
    # shard by rank (per-worker dataset sharding)
    x, y = x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()]

    model = keras.Sequential([
        keras.Input((28, 28, 1)),
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10),
    ])

    # scale LR by world size; wrap so gradients allreduce before apply
    opt = hvd.DistributedOptimizer(
        keras.optimizers.Adam(args.lr * hvd.size()))
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"])

    callbacks = [
        # rank 0's initial weights win everywhere
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=args.lr * hvd.size(), warmup_epochs=1, verbose=0),
    ]
    hist = model.fit(x, y, batch_size=args.batch, epochs=args.epochs,
                     callbacks=callbacks, verbose=2 if hvd.rank() == 0 else 0)
    if hvd.rank() == 0:
        model.save("/tmp/keras_mnist_hvd.keras")
        print(f"final loss {hist.history['loss'][-1]:.4f}")


if __name__ == "__main__":
    main()
