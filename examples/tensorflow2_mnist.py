"""TF2 MNIST with DistributedGradientTape — BASELINE.md tracked config 2
(reference examples/tensorflow2/tensorflow2_mnist.py usage shape:
init → shard data by rank → tape-wrapped gradients → broadcast variables
on first step → rank-0-only checkpoints).

Run:  hvdrun -np 2 python examples/tensorflow2_mnist.py --steps 50
"""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def synthetic_mnist(n=2048, seed=0):
    """Deterministic MNIST-shaped data (no dataset download in CI)."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.int64)
    # make it learnable: brighten a quadrant per class
    for i in range(n):
        q = y[i] % 4
        r, c = divmod(q, 2)
        x[i, r * 14:(r + 1) * 14, c * 14:(c + 1) * 14] += y[i] / 10.0
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    hvd.init()

    x, y = synthetic_mnist()
    # shard by rank (Horovod-style per-worker dataset sharding)
    x, y = x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()]
    dataset = (tf.data.Dataset.from_tensor_slices((x, y))
               .repeat().shuffle(1024, seed=hvd.rank())
               .batch(args.batch))

    import keras
    keras.utils.set_random_seed(42 + hvd.rank())  # deliberately different
    model = keras.Sequential([
        keras.layers.Conv2D(16, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10),
    ])
    loss_fn = keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    # scale LR by world size (Horovod's linear-scaling convention)
    opt = keras.optimizers.Adam(args.lr * hvd.size())

    for step, (images, labels) in enumerate(dataset.take(args.steps)):
        with tf.GradientTape() as tape:
            logits = model(images, training=True)
            loss = loss_fn(labels, logits)
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # after the first step (variables now exist): align all workers
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss={float(loss):.4f}")

    # global accuracy via metric allreduce
    logits = model(x[:512], training=False)
    acc = float(np.mean(np.argmax(logits.numpy(), -1) == y[:512]))
    acc = float(hvd.allreduce(tf.constant(acc), average=True,
                              name="final.acc").numpy())
    if hvd.rank() == 0:
        print(f"final accuracy (global avg): {acc:.3f}")


if __name__ == "__main__":
    main()
