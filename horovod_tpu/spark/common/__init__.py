from .store import FilesystemStore, HDFSStore, LocalStore, Store  # noqa: F401
