"""Training-artifact store abstraction (reference
/root/reference/horovod/spark/common/store.py:32 Store / :157
FilesystemStore/LocalStore/HDFSStore).

Original slim implementation: the store maps (run_id, dataset index) to
paths for intermediate data, checkpoints and logs on a filesystem-like
backend. The local filesystem backend is fully functional (and is what the
TPU estimator uses for orbax/np checkpoints); an HDFS backend is gated on
pyarrow having HDFS support in the environment.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

from ...common.util import atomic_write_bytes


class Store:
    """Abstract path layout + object IO for estimator runs."""

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes):
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str):
        """Factory (reference store.py Store.create): pick a backend from
        the path scheme."""
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path)
        return FilesystemStore(prefix_path)


class FilesystemStore(Store):
    """Local/NFS filesystem layout (reference FilesystemStore :157)."""

    def __init__(self, prefix_path: str):
        self.prefix_path = os.path.abspath(prefix_path)
        os.makedirs(self.prefix_path, exist_ok=True)

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        sub = "intermediate_train_data" + ("" if idx is None else f".{idx}")
        return os.path.join(self.prefix_path, sub)

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        sub = "intermediate_val_data" + ("" if idx is None else f".{idx}")
        return os.path.join(self.prefix_path, sub)

    def get_runs_path(self) -> str:
        return os.path.join(self.prefix_path, "runs")

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.get_runs_path(), run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes):
        # Every hvdrun worker stages the same chunks to the same store
        # concurrently (keras.py _fit_from_store): last intact writer
        # wins via the shared atomic-replace helper.
        atomic_write_bytes(path, data)

    def cleanup_run(self, run_id: str):
        shutil.rmtree(self.get_run_path(run_id), ignore_errors=True)


class LocalStore(FilesystemStore):
    """Alias of FilesystemStore (reference LocalStore)."""


class HDFSStore(Store):
    """HDFS-backed store via pyarrow (reference HDFSStore). Gated: raises
    at construction when the environment has no HDFS support."""

    def __init__(self, prefix_path: str, host: str = "default",
                 port: int = 0, user: Optional[str] = None):
        try:
            from pyarrow import fs as pafs

            self._fs = pafs.HadoopFileSystem(host=host, port=port, user=user)
        except Exception as e:
            raise ImportError(
                "HDFSStore requires pyarrow with libhdfs support; use "
                "FilesystemStore for local/NFS paths") from e
        self.prefix_path = prefix_path

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        sub = "intermediate_train_data" + ("" if idx is None else f".{idx}")
        return f"{self.prefix_path}/{sub}"

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        sub = "intermediate_val_data" + ("" if idx is None else f".{idx}")
        return f"{self.prefix_path}/{sub}"

    def get_runs_path(self) -> str:
        return f"{self.prefix_path}/runs"

    def get_run_path(self, run_id: str) -> str:
        return f"{self.get_runs_path()}/{run_id}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/checkpoint"

    def get_logs_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/logs"

    def exists(self, path: str) -> bool:
        from pyarrow import fs as pafs

        return self._fs.get_file_info(path).type != pafs.FileType.NotFound

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open_input_stream(path) as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes):
        with self._fs.open_output_stream(path) as f:
            f.write(data)
