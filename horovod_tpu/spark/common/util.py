"""DataFrame materialization helpers for the Spark estimators.

Reference: /root/reference/horovod/spark/common/util.py (747 LoC) prepares
DataFrames by writing Parquet/Petastorm stores and building per-rank
readers. TPU-native slimming: the estimators here materialize features to
NumPy (the universal currency of jax/torch/keras) — a pandas DataFrame is
handled directly, a pyspark DataFrame via ``toPandas()`` (small/medium
data) so the estimator API works with or without a live Spark cluster.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def _is_spark_df(df) -> bool:
    mod = type(df).__module__
    return mod.startswith("pyspark.")


def to_pandas(df):
    """pandas passthrough; pyspark → toPandas() (driver-side collect)."""
    if _is_spark_df(df):
        return df.toPandas()
    return df


def dataframe_to_numpy(df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       dtype=np.float32,
                       label_dtype=None) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Materialize ``df[feature_cols]`` (and labels) as dense arrays.

    Columns holding vectors (lists/ndarrays per row) are stacked; scalar
    columns become width-1 features and are concatenated in column order
    (the moral of reference util.py's petastorm schema prep, without the
    Parquet round-trip).

    Labels preserve integer column dtypes by default (the reference's
    petastorm path keeps column types; integer-target losses like
    CrossEntropyLoss need integer classes, not float32). ``label_dtype``
    forces a specific label dtype.
    """
    pdf = to_pandas(df)

    def target_dtype(col_dtype, explicit, preserve_int):
        if explicit is not None:
            return explicit
        if preserve_int and np.issubdtype(col_dtype, np.integer):
            return col_dtype
        return dtype

    def cols_to_array(cols, explicit=None, preserve_int=False) -> np.ndarray:
        parts = []
        for c in cols:
            v = pdf[c].to_numpy()
            if v.dtype == object:  # per-row vectors
                tgt = target_dtype(np.asarray(v[0]).dtype, explicit,
                                   preserve_int)
                part = np.stack([np.asarray(e, dtype=tgt) for e in v])
                if part.ndim == 1:
                    part = part[:, None]
            else:
                part = v.astype(target_dtype(v.dtype, explicit,
                                             preserve_int))[:, None]
            parts.append(part)
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)

    x = cols_to_array(list(feature_cols))
    y = (cols_to_array(list(label_cols), explicit=label_dtype,
                       preserve_int=True)
         if label_cols else None)
    return x, y


def attach_predictions(pdf, out: np.ndarray, output_cols: Sequence[str]):
    """Write model outputs into DataFrame columns (shared by the torch and
    keras model transformers).

    - one output column + multi-width output → each row stores the full
      output vector (reference estimators keep the row vector);
    - k output columns + width-k output → one scalar column each;
    - anything else is ambiguous → error, never silent truncation.
    """
    if out.ndim == 1:
        out = out[:, None]
    cols = list(output_cols)
    if len(cols) == 1:
        if out.shape[1] == 1:
            pdf[cols[0]] = list(out[:, 0])
        else:
            pdf[cols[0]] = list(out)
    elif len(cols) == out.shape[1]:
        for i, c in enumerate(cols):
            pdf[c] = list(out[:, i])
    else:
        raise ValueError(
            f"{len(cols)} output_cols for model output width {out.shape[1]}")
    return pdf


def train_val_split(x: np.ndarray, y: Optional[np.ndarray],
                    validation: Optional[float]):
    """Tail-fraction validation split (reference estimators accept a
    ``validation`` fraction/column; only the fraction form is kept)."""
    if not validation:
        return (x, y), (None, None)
    n = len(x)
    n_val = max(1, int(n * float(validation)))
    cut = n - n_val
    val_y = y[cut:] if y is not None else None
    trn_y = y[:cut] if y is not None else None
    return (x[:cut], trn_y), (x[cut:], val_y)
