"""Store-backed staged datasets: the out-of-memory Spark → TPU data path.

Reference: /root/reference/horovod/spark/common/util.py:747 (`prepare_data`)
stages DataFrames to Parquet through the Store, and Petastorm streams
row-groups to each rank so no worker ever materializes the whole dataset.

TPU-native slimming of the same contract:

- ``stage_dataframe`` writes the DataFrame through the ``Store`` in
  chunks — **Parquet** chunks (via pyarrow, matching the reference's
  columnar materialization, util.py:747) when pyarrow is importable, and
  compressed ``.npz`` otherwise (dense numpy is the universal currency of
  the jax/torch/keras estimators here). Parquet chunks keep the original
  column names/types, so the staged store is readable by any Parquet
  tool, not just this framework. A pyspark DataFrame is consumed via
  ``toLocalIterator()`` — partition at a time, never a whole collect; a
  pandas DataFrame is sliced. Chunks are the row-group analogue.
- ``StoreDataset`` is the per-rank streaming reader: it owns the chunks
  with ``index % num_shards == shard_id`` (reference petastorm
  ``cur_shard/shard_count``) and holds ONE chunk in memory at a time.

Epoch symmetry: distributed training needs every rank to run the same
number of optimizer steps (each step allreduces). ``min_shard_batches``
computes, from the staged metadata alone, the largest per-epoch step count
every shard can serve — ranks truncate to it deterministically, with no
extra negotiation round.
"""

from __future__ import annotations

import io
import json
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from .util import _is_spark_df, dataframe_to_numpy

META_FILE = "meta.json"


def have_pyarrow() -> bool:
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401

        return True
    except Exception:
        return False


def _chunk_file(i: int, fmt: str = "npz") -> str:
    return f"chunk_{i:06d}.{'parquet' if fmt == 'parquet' else 'npz'}"


def _arrow_table(pdf_part, cols):
    """pandas chunk → pyarrow Table. Vector-valued cells (pyspark
    DenseVector, ndarray) are opaque objects to pyarrow — normalize them
    to plain lists so the chunk is a standard list<float> Parquet column."""
    import pyarrow as pa

    part = pdf_part[cols].copy()
    for c in part.columns:
        if part[c].dtype == object:
            part[c] = part[c].map(lambda e: np.asarray(e).tolist())
    return pa.Table.from_pandas(part, preserve_index=False)


def stage_dataframe(df, store, path: str, feature_cols: Sequence[str],
                    label_cols: Optional[Sequence[str]] = None,
                    dtype=np.float32, label_dtype=None,
                    chunk_rows: int = 4096,
                    format: Optional[str] = None) -> dict:
    """Write ``df`` through ``store`` as chunks under ``path``.

    ``format``: ``"parquet"`` (columnar chunks via pyarrow — the
    reference's materialization format, spark/common/util.py:747),
    ``"npz"`` (compressed dense arrays), or None to pick parquet when
    pyarrow is importable. Returns (and persists as ``path/meta.json``)
    the dataset metadata: ``format``, ``n_rows``, ``n_chunks``,
    ``chunk_rows`` (per-chunk row counts), feature/label shapes and
    dtypes. Idempotent restaging is the caller's concern (check
    ``store.exists(meta_path(path))`` first).
    """
    auto_format = format is None
    if auto_format:
        format = "parquet" if have_pyarrow() else "npz"
    if format not in ("parquet", "npz"):
        raise ValueError(f"unknown staging format {format!r}")
    if format == "parquet" and not have_pyarrow():
        raise ValueError("format='parquet' requires pyarrow")
    state = {"n_rows": 0, "chunks": [], "x_shape": None, "x_dtype": None,
             "y_shape": None, "y_dtype": None, "format": format}
    cols = list(feature_cols) + list(label_cols or [])

    def flush(pdf_part):
        # shapes/dtypes recorded from the same conversion the reader uses
        x, y = dataframe_to_numpy(pdf_part, feature_cols, label_cols,
                                  dtype=dtype, label_dtype=label_dtype)
        buf = io.BytesIO()
        if state["format"] == "parquet":
            import pyarrow.parquet as pq

            try:
                # original columns, not pre-flattened tensors: the staged
                # store stays a plain Parquet dataset any tool can read
                table = _arrow_table(pdf_part, cols)
            except Exception as e:
                if not auto_format or state["chunks"]:
                    # explicitly requested, or some chunks already
                    # staged (a silent mid-dataset format flip would mix
                    # formats): surface the conversion problem
                    raise ValueError(
                        "parquet staging could not convert a chunk "
                        f"({type(e).__name__}: {e}); pass format='npz' "
                        "or normalize the offending column") from e
                # auto-selected and nothing written yet: npz handles
                # anything dataframe_to_numpy can
                state["format"] = "npz"
        if state["format"] == "parquet":
            pq.write_table(table, buf)
        else:
            arrays = {"x": x}
            if y is not None:
                arrays["y"] = y
            np.savez_compressed(buf, **arrays)
        i = len(state["chunks"])
        store.write_bytes(f"{path}/{_chunk_file(i, state['format'])}",
                          buf.getvalue())
        state["chunks"].append(len(x))
        state["n_rows"] += len(x)
        state["x_shape"], state["x_dtype"] = list(x.shape[1:]), str(x.dtype)
        if y is not None:
            state["y_shape"], state["y_dtype"] = list(y.shape[1:]), str(y.dtype)

    if _is_spark_df(df):
        import pandas as pd

        rows = []
        for row in df.toLocalIterator():  # streams partitions, no collect
            rows.append(row.asDict())
            if len(rows) >= chunk_rows:
                flush(pd.DataFrame(rows))
                rows = []
        if rows:
            flush(pd.DataFrame(rows))
    else:
        for i in range(0, len(df), chunk_rows):
            flush(df.iloc[i:i + chunk_rows])

    meta = {
        "format": state["format"],
        "n_rows": state["n_rows"],
        "n_chunks": len(state["chunks"]),
        "chunk_rows": state["chunks"],
        "x_shape": state["x_shape"], "x_dtype": state["x_dtype"],
        "y_shape": state["y_shape"], "y_dtype": state["y_dtype"],
        "feature_cols": list(feature_cols),
        "label_cols": list(label_cols or []),
    }
    store.write_bytes(f"{path}/{META_FILE}", json.dumps(meta).encode())
    return meta


def meta_path(path: str) -> str:
    return f"{path}/{META_FILE}"


def load_meta(store, path: str) -> dict:
    return json.loads(store.read_bytes(meta_path(path)))


class StoreDataset:
    """Per-rank streaming view over a staged dataset.

    Shards at chunk granularity (reference petastorm shards row-groups via
    ``cur_shard``/``shard_count``); ``batches`` holds one chunk in memory
    at a time. ``max_rows_resident`` records the largest single load —
    the no-whole-materialization property tests assert on.
    """

    def __init__(self, store, path: str, shard_id: int = 0,
                 num_shards: int = 1,
                 chunks: Optional[Sequence[int]] = None):
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
        self.store = store
        self.path = path
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.meta = load_meta(store, path)
        # the chunk universe this dataset covers (a train/val split reserves
        # disjoint chunk subsets), dealt round-robin to shards. With fewer
        # than 2 chunks per shard, whole-chunk dealing would leave shards
        # empty or badly unbalanced — fall back to row-in-chunk sharding
        # (every shard reads every chunk, keeps rows [shard_id::num_shards];
        # still one chunk resident at a time, at the cost of n× chunk IO).
        self._all = (list(chunks) if chunks is not None
                     else list(range(self.meta["n_chunks"])))
        self.row_sharded = len(self._all) < 2 * num_shards and num_shards > 1
        if self.row_sharded:
            self._chunks = list(self._all)
        else:
            self._chunks = [c for j, c in enumerate(self._all)
                            if j % num_shards == shard_id]
        self.max_rows_resident = 0

    def _shard_rows(self, sid: int) -> int:
        if self.row_sharded:
            return sum(len(range(sid, self.meta["chunk_rows"][c],
                                 self.num_shards)) for c in self._all)
        return sum(self.meta["chunk_rows"][c]
                   for j, c in enumerate(self._all)
                   if j % self.num_shards == sid)

    def __len__(self) -> int:
        """Rows owned by this shard."""
        return self._shard_rows(self.shard_id)

    @property
    def total_rows(self) -> int:
        return sum(self.meta["chunk_rows"][i] for i in self._all)

    def shard_batches(self, batch_size: int, shard_id: Optional[int] = None
                      ) -> int:
        """Per-epoch full+partial batch count a shard can serve."""
        rows = self._shard_rows(self.shard_id if shard_id is None
                                else shard_id)
        return -(-rows // batch_size) if rows else 0

    def min_shard_batches(self, batch_size: int) -> int:
        """Largest per-epoch step count EVERY shard can serve — ranks
        truncate to this so per-step collectives stay symmetric."""
        return min(self.shard_batches(batch_size, s)
                   for s in range(self.num_shards))

    def _decode_chunk(self, blob: bytes
                      ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if self.meta.get("format", "npz") == "parquet":
            import pyarrow.parquet as pq

            pdf = pq.read_table(io.BytesIO(blob)).to_pandas()
            return dataframe_to_numpy(
                pdf, self.meta["feature_cols"],
                self.meta["label_cols"] or None,
                dtype=np.dtype(self.meta["x_dtype"]),
                label_dtype=(np.dtype(self.meta["y_dtype"])
                             if self.meta.get("y_dtype") else None))
        z = np.load(io.BytesIO(blob), allow_pickle=False)
        return z["x"], (z["y"] if "y" in z.files else None)

    def iter_chunks(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        fmt = self.meta.get("format", "npz")
        for ci in self._chunks:
            blob = self.store.read_bytes(f"{self.path}/{_chunk_file(ci, fmt)}")
            x, y = self._decode_chunk(blob)
            self.max_rows_resident = max(self.max_rows_resident, len(x))
            if self.row_sharded:
                x = x[self.shard_id::self.num_shards]
                y = y[self.shard_id::self.num_shards] if y is not None else None
                if not len(x):
                    continue
            yield x, y

    def batches(self, batch_size: int, shuffle_seed: Optional[int] = None,
                limit: Optional[int] = None
                ) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """Stream (x, y) batches from this shard's chunks.

        ``shuffle_seed`` shuffles chunk order and rows within each chunk
        (petastorm's shuffle granularity: row-groups + in-group buffer) —
        pass a per-epoch seed for epoch-varying order. ``limit`` truncates
        to that many batches (see ``min_shard_batches``).
        """
        rng = (np.random.RandomState(shuffle_seed)
               if shuffle_seed is not None else None)
        order = list(self._chunks)
        if rng is not None:
            rng.shuffle(order)
        emitted = 0
        saved, self._chunks = self._chunks, order
        try:
            for x, y in self.iter_chunks():
                if rng is not None:
                    perm = rng.permutation(len(x))
                    x = x[perm]
                    y = y[perm] if y is not None else None
                for i in range(0, len(x), batch_size):
                    if limit is not None and emitted >= limit:
                        return
                    yield (x[i:i + batch_size],
                           y[i:i + batch_size] if y is not None else None)
                    emitted += 1
        finally:
            self._chunks = saved
