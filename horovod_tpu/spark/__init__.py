"""horovod_tpu.spark — Spark cluster integration (reference
horovod/spark/: runner.py:195 ``run``, :306 ``run_elastic``, plus the
Estimator API).

``run(fn, ...)`` executes ``fn`` once per Spark executor task, using the
Spark driver as the rendezvous host (reference spark/runner.py's
driver-service pattern, re-expressed over the HTTP KV store +
``jax.distributed``). Gated on pyspark: this environment has no Spark, so
the entry points raise a clear ImportError while the spark-free pieces
(`horovod_tpu.spark.common.store`, the estimator's checkpoint layout)
stay importable and tested.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from .common.store import FilesystemStore, HDFSStore, LocalStore, Store  # noqa: F401


def __getattr__(name):
    # estimators import torch/keras lazily; expose them at package level
    # (reference: horovod.spark.keras.KerasEstimator,
    # horovod.spark.torch.TorchEstimator)
    if name in ("TorchEstimator", "TorchModel"):
        from . import torch as _torch_mod

        return getattr(_torch_mod, name)
    if name in ("KerasEstimator", "KerasModel"):
        from . import keras as _keras_mod

        return getattr(_keras_mod, name)
    raise AttributeError(name)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark, which is not installed "
            "in this environment. The store/estimator utilities "
            "(horovod_tpu.spark.common) work without it.") from e


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, start_timeout: float = 600,
        env: Optional[dict] = None, stdout=None, stderr=None,
        verbose: int = 1, prefix_output_with_timestamp: bool = False):
    """Run ``fn`` on ``num_proc`` Spark tasks (reference
    spark/runner.py:195). One task per executor; ranks/topology follow the
    executor placement; the driver hosts the rendezvous server."""
    pyspark = _require_pyspark()
    from pyspark import SparkContext

    from ..common import env as env_schema
    from ..ray.runner import Coordinator  # same topology computation
    from ..runner.http_server import RendezvousServer

    sc = SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create one first")
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)

    # Probe executor hostnames with a first barrier stage, compute rank
    # envs on the driver, then run the real job stage.
    hosts = (sc.parallelize(range(num_proc), num_proc)
             .map(lambda _: __import__("socket").gethostname()).collect())
    coord = Coordinator()
    for rank, h in enumerate(hosts):
        coord.register(h, rank)
    envs = coord.rank_envs()
    from ..runner.secret import get_or_mint_env_secret

    job_secret = get_or_mint_env_secret()  # before the server binds its key
    rendezvous = RendezvousServer()
    port = rendezvous.start()
    import socket

    addr = socket.gethostbyname(socket.gethostname())
    base_env = dict(env or {})
    for e in envs.values():
        e.update(base_env)
        e[env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR] = addr
        e[env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT] = str(port)
        e[env_schema.HOROVOD_SECRET_KEY] = job_secret

    fn_args, fn_kwargs = args, kwargs or {}

    def task(it):
        idx = next(iter(it))
        os.environ.update(envs[idx])
        return [fn(*fn_args, **fn_kwargs)]

    try:
        return (sc.parallelize(range(num_proc), num_proc)
                .mapPartitions(task).collect())
    finally:
        rendezvous.stop()


def run_elastic(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
                num_proc: Optional[int] = None, min_np: Optional[int] = None,
                max_np: Optional[int] = None, env: Optional[dict] = None,
                **_):
    """Elastic variant (reference spark/runner.py:306) over the shared
    elastic function executor. Worker placement is LOCAL: every slot runs
    as a subprocess on the driver host (the executor's engine — same
    limitation as the Ray elastic adapter, see ray/elastic.py docstring).
    A live SparkContext only contributes the default process count; without
    pyspark, pass ``num_proc`` explicitly for the same contract."""
    from ..elastic.discovery import FixedHosts
    from ..elastic.executor import ElasticFunctionExecutor

    if num_proc is None:
        pyspark = _require_pyspark()
        from pyspark import SparkContext

        sc = SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError("no active SparkContext; create one first")
        num_proc = max(int(sc.defaultParallelism), 1)
    discovery = FixedHosts({"localhost": num_proc})

    settings = ElasticFunctionExecutor.create_settings(
        min_np=min_np or num_proc, max_np=max_np or num_proc)
    ex = ElasticFunctionExecutor(settings, discovery, env_vars=env)
    ex.start()
    try:
        return ex.run(fn, args, kwargs)
    finally:
        ex.shutdown()
