"""Keras Estimator for Spark DataFrames (reference
horovod/spark/keras/estimator.py:558 KerasEstimator → HorovodModel).

The estimator carries a model + optimizer + Store; ``fit`` materializes
the DataFrame and trains one worker per executor (gated on pyspark);
checkpoints ride the Store abstraction, which works standalone.
"""

from __future__ import annotations

from typing import Optional

from .common.store import Store


class KerasEstimator:
    def __init__(self, model=None, optimizer=None, loss=None, metrics=None,
                 store: Optional[Store] = None, num_proc: Optional[int] = None,
                 batch_size: int = 32, epochs: int = 1,
                 feature_cols=None, label_cols=None, run_id: str = "run0",
                 verbose: int = 1):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics or []
        self.store = store
        self.num_proc = num_proc
        self.batch_size = batch_size
        self.epochs = epochs
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id
        self.verbose = verbose

    def checkpoint_path(self) -> str:
        if self.store is None:
            raise ValueError("estimator needs a store for checkpoints")
        return self.store.get_checkpoint_path(self.run_id)

    def save_checkpoint(self):
        """Serialize the Keras model into the store (rank-0 convention)."""
        import io

        if self.model is None:
            raise ValueError("no model to checkpoint")
        buf = io.BytesIO()
        import keras

        # keras 3 saves to a file path; round-trip through a temp file
        import os
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.keras")
            self.model.save(p)
            with open(p, "rb") as f:
                buf.write(f.read())
        self.store.write_bytes(self.checkpoint_path(), buf.getvalue())

    def load_checkpoint(self):
        import os
        import tempfile

        import keras

        data = self.store.read_bytes(self.checkpoint_path())
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.keras")
            with open(p, "wb") as f:
                f.write(data)
            return keras.models.load_model(p)

    def fit(self, df):
        """Train on a Spark DataFrame (requires pyspark; reference
        estimator.fit → per-executor training loop)."""
        from . import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "DataFrame materialization requires a live Spark cluster")
