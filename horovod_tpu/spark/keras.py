"""Keras Estimator for Spark DataFrames (reference
horovod/spark/keras/estimator.py:558 KerasEstimator → HorovodModel).

The estimator carries a model + optimizer + Store; ``fit`` materializes
the DataFrame and trains one worker per executor (gated on pyspark);
checkpoints ride the Store abstraction, which works standalone.
"""

from __future__ import annotations

from typing import Optional

from ..common import env as env_schema
from .common.store import Store


class KerasEstimator:
    def __init__(self, model=None, optimizer=None, loss=None, metrics=None,
                 store: Optional[Store] = None, num_proc: Optional[int] = None,
                 batch_size: int = 32, epochs: int = 1,
                 feature_cols=None, label_cols=None, run_id: str = "run0",
                 verbose: int = 1, backend_env: Optional[dict] = None,
                 label_dtype=None, staging_chunk_rows: int = 4096,
                 validation: Optional[float] = None,
                 resume_from_checkpoint: bool = False,
                 sample_weight_col: Optional[str] = None,
                 custom_objects: Optional[dict] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = metrics or []
        self.store = store
        self.num_proc = num_proc
        self.batch_size = batch_size
        self.epochs = epochs
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id
        self.verbose = verbose
        # extra env for estimator-launched workers (e.g. JAX_PLATFORMS)
        self.backend_env = dict(backend_env or {})
        # None: integer label columns stay integer (sparse CE targets)
        self.label_dtype = label_dtype
        # rows per staged npz chunk on the store-backed data path
        self.staging_chunk_rows = staging_chunk_rows
        # fraction of rows held out for per-epoch validation (reference
        # keras estimator validation param)
        self.validation = validation
        # continue a killed run from its last per-epoch checkpoint
        # (reference keras/remote.py restores the checkpoint and resumes
        # at initial_epoch)
        self.resume_from_checkpoint = resume_from_checkpoint
        self.history: dict = {}
        # reference estimator params: per-row fit weights and the
        # custom_objects dict for deserializing user layers/losses
        # (reference keras estimator sample_weight_col /
        # custom_objects)
        self.sample_weight_col = sample_weight_col
        self.custom_objects = dict(custom_objects or {})
        self._best_score = float("inf")  # best monitored loss so far

    def checkpoint_path(self) -> str:
        if self.store is None:
            raise ValueError("estimator needs a store for checkpoints")
        return self.store.get_checkpoint_path(self.run_id)

    def best_checkpoint_path(self) -> str:
        return self.checkpoint_path() + ".best"

    def _meta_path(self) -> str:
        return self.checkpoint_path() + ".meta"

    def save_checkpoint(self, epoch: Optional[int] = None,
                        path: Optional[str] = None):
        """Serialize the Keras model into the store (rank-0 convention;
        reference keras/remote.py writes the checkpoint every epoch). The
        ``.keras`` archive carries optimizer state, so a resumed fit
        continues the same optimizer trajectory; epoch + history ride a
        JSON sidecar."""
        import io
        import json

        if self.model is None:
            raise ValueError("no model to checkpoint")
        buf = io.BytesIO()
        # keras 3 saves to a file path; round-trip through a temp file
        import os
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.keras")
            self.model.save(p)
            with open(p, "rb") as f:
                buf.write(f.read())
        self.store.write_bytes(path or self.checkpoint_path(),
                               buf.getvalue())
        if epoch is not None and path is None:
            self.store.write_bytes(self._meta_path(), json.dumps(
                {"epoch": epoch, "history": self.history,
                 "best": self._best_score}).encode())

    def load_checkpoint(self, best: bool = False):
        """Restore the model from the store; returns the model. The epoch
        to resume FROM lands in ``self._resume_epoch``."""
        import json
        import os
        import tempfile

        import keras

        path = self.best_checkpoint_path() if best else self.checkpoint_path()
        data = self.store.read_bytes(path)
        self._resume_epoch = 0
        if not best and self.store.exists(self._meta_path()):
            meta = json.loads(self.store.read_bytes(self._meta_path()))
            self._resume_epoch = int(meta.get("epoch", -1)) + 1
            self.history = dict(meta.get("history") or {})
            if meta.get("best") is not None:
                # the pre-crash best survives the resume: a worse first
                # post-resume epoch must NOT overwrite the .best model
                self._best_score = float(meta["best"])
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.keras")
            with open(p, "wb") as f:
                f.write(data)
            return keras.models.load_model(
                p, custom_objects=self.custom_objects or None)

    def _store_callbacks(self, hvd_keras=None, distributed=False) -> list:
        """Per-epoch checkpoint + best-model tracking as a Keras callback
        (reference remote.py: rank 0 saves after every epoch)."""
        if self.store is None:
            return []
        if distributed and hvd_keras.cross_rank() != 0:
            return []
        import keras

        est = self

        class _StoreCheckpoint(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                logs = logs or {}
                for k, v in logs.items():
                    est.history.setdefault(k, []).append(float(v))
                score = logs.get("val_loss", logs.get("loss"))
                # est._best_score persists through resume (meta sidecar),
                # so a worse post-resume epoch keeps the pre-crash best
                if score is not None and float(score) <= est._best_score:
                    est._best_score = float(score)
                    est.save_checkpoint(path=est.best_checkpoint_path())
                est.save_checkpoint(epoch=epoch)

        return [_StoreCheckpoint()]

    def fit(self, df):
        """Train on a pandas or pyspark DataFrame (reference estimator.fit
        → per-executor training loop; see spark/torch.py for the
        materialization model). Returns a ``KerasModel`` transformer."""
        from .common.util import dataframe_to_numpy

        if self.model is None or not self.feature_cols or not self.label_cols:
            raise ValueError("model, feature_cols and label_cols are required")
        if self.optimizer is not None or self.loss is not None:
            # fill the unspecified half from the model's existing compile
            # config; silently substituting a default (e.g. "mse" on a
            # classifier) would train the wrong objective, so a missing
            # half with no prior config is an error
            opt = self.optimizer or getattr(self.model, "optimizer", None)
            loss = self.loss or getattr(self.model, "loss", None)
            if opt is None or loss is None:
                raise ValueError(
                    "estimator got only one of optimizer/loss and the "
                    "model has no prior compile config for the other")
            self.model.compile(optimizer=opt, loss=loss,
                               metrics=self.metrics)
        elif not getattr(self.model, "compiled", False):
            raise ValueError(
                "model is not compiled; pass optimizer= and loss= to the "
                "estimator or compile the model first")
        import os

        if self.store is not None:
            # store-backed path: stage through the Store, stream per-rank
            # chunks (reference spark/common/util.py:747 + petastorm)
            if self.sample_weight_col:
                raise ValueError(
                    "sample_weight_col is supported on the in-memory "
                    "(pandas) path; the store staging format carries "
                    "features+labels only")
            return self._fit_from_store(df)
        from .common.util import to_pandas

        if (self.sample_weight_col and self.num_proc and self.num_proc > 1
                and env_schema.HOROVOD_RANK not in os.environ):
            # fail BEFORE the driver-side collect (see spark/torch.py)
            raise ValueError(
                "sample_weight_col with estimator-launched num_proc "
                "is not supported; launch with hvdrun instead")
        # collect ONCE (see spark/torch.py: a second toPandas() of an
        # unordered plan can misalign weights with features)
        pdf = to_pandas(df)
        x, y = dataframe_to_numpy(pdf, self.feature_cols, self.label_cols,
                                  label_dtype=self.label_dtype)
        w = None
        if self.sample_weight_col:
            import numpy as np

            w = pdf[self.sample_weight_col].to_numpy(np.float32)
        if (self.num_proc and self.num_proc > 1
                and env_schema.HOROVOD_RANK not in os.environ):
            # (sample_weight_col was rejected before the collect above)
            return self._fit_multiproc(x, y)

        # under a launcher (hvdrun): data-parallel in-process fit — wrap
        # the compiled optimizer, shard, broadcast initial weights, and
        # let only rank 0 touch the shared checkpoint (mirrors the torch
        # estimator's distributed branch)
        import horovod_tpu.keras as hvd_keras

        distributed = False
        if env_schema.HOROVOD_RANK in os.environ:
            if not hvd_keras.is_initialized():
                hvd_keras.init()
            distributed = hvd_keras.cross_size() > 1
        # (no store handling here: fit() dispatched to _fit_from_store
        # above whenever a store is present, and that path owns
        # checkpointing + resume)
        self.history = {}
        callbacks = []
        if distributed:
            self._compile_distributed(hvd_keras)
            r, n = hvd_keras.cross_rank(), hvd_keras.cross_size()
            x, y = x[r::n], y[r::n]
            w = w[r::n] if w is not None else None
            callbacks = [
                hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
                hvd_keras.callbacks.MetricAverageCallback()]
        hist = self.model.fit(
            x, y, batch_size=self.batch_size, epochs=self.epochs,
            sample_weight=w,
            validation_split=float(self.validation or 0.0),
            callbacks=callbacks, verbose=self.verbose)
        self.history = {k: [float(v) for v in vs]
                        for k, vs in hist.history.items()}
        return KerasModel(self.model, self.feature_cols,
                          history=self.history)

    def _compile_distributed(self, hvd_keras):
        """Wrap the model's compiled optimizer for gradient allreduce,
        preserving the model's own compiled metrics when the estimator
        didn't specify any (re-compiling with [] would silently drop e.g.
        accuracy from a user-pre-compiled model)."""
        if getattr(self.model.optimizer.__class__, "_hvd_wrapped", False):
            return
        metrics = self.metrics
        if not metrics:
            try:
                cfg = self.model.get_compile_config() or {}
                m = cfg.get("metrics")
                if m:
                    import keras

                    metrics = [keras.metrics.deserialize(e)
                               if isinstance(e, dict) else e
                               for e in m]
            except Exception:
                metrics = None
        self.model.compile(
            optimizer=hvd_keras.DistributedOptimizer(self.model.optimizer),
            loss=self.model.loss, metrics=metrics or None)

    # -- store-backed streaming path (reference util.py:747 + petastorm) ----
    def _fit_from_store(self, df) -> "KerasModel":
        import os

        from .common.datamodule import (StoreDataset, meta_path,
                                        stage_dataframe)

        train_path = self.store.get_train_data_path()
        if df is not None:
            stage_dataframe(df, self.store, train_path, self.feature_cols,
                            self.label_cols, label_dtype=self.label_dtype,
                            chunk_rows=self.staging_chunk_rows)
        elif not self.store.exists(meta_path(train_path)):
            raise ValueError("no staged dataset in the store and no "
                             "DataFrame to stage")
        if (self.num_proc and self.num_proc > 1
                and env_schema.HOROVOD_RANK not in os.environ):
            return self._fit_multiproc_store()

        import horovod_tpu.keras as hvd_keras

        from .common.datamodule import load_meta

        distributed = False
        if env_schema.HOROVOD_RANK in os.environ:
            if not hvd_keras.is_initialized():
                hvd_keras.init()
            distributed = hvd_keras.cross_size() > 1
        r = hvd_keras.cross_rank() if distributed else 0
        n = hvd_keras.cross_size() if distributed else 1
        self.history = {}
        initial_epoch = 0
        if (self.resume_from_checkpoint
                and self.store.exists(self.checkpoint_path())):
            self.model = self.load_checkpoint()
            initial_epoch = self._resume_epoch
        callbacks = []
        if distributed:
            self._compile_distributed(hvd_keras)
            callbacks = [
                hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
                hvd_keras.callbacks.MetricAverageCallback()]
        # validation reserves whole tail chunks (same scheme as the torch
        # estimator's store path)
        n_chunks = load_meta(self.store, train_path)["n_chunks"]
        n_val = 0
        if self.validation:
            if n_chunks < 2:
                raise ValueError(
                    "validation split on the store path reserves whole "
                    "chunks; stage at least 2 (lower staging_chunk_rows)")
            n_val = max(1, round(float(self.validation) * n_chunks))
            n_val = min(n_val, n_chunks - 1)
        ds = StoreDataset(self.store, train_path, shard_id=r, num_shards=n,
                          chunks=list(range(n_chunks - n_val)))
        self.last_train_dataset = ds  # observability for streaming tests
        steps = (ds.min_shard_batches(self.batch_size) if distributed
                 else ds.shard_batches(self.batch_size))
        if steps < 1:
            raise ValueError("staged dataset has no rows for this shard")

        def gen():
            epoch = 0
            while True:
                for xb, yb in ds.batches(self.batch_size,
                                         shuffle_seed=epoch,
                                         limit=steps):
                    yield xb, yb
                epoch += 1

        fit_kwargs = {}
        if n_val:
            # validation shards across ranks too (MetricAverageCallback
            # averages the shard means); vsteps uses the min shard so
            # every rank runs the same count
            val_ds = StoreDataset(
                self.store, train_path, shard_id=r, num_shards=n,
                chunks=list(range(n_chunks - n_val, n_chunks)))
            vsteps = (val_ds.min_shard_batches(self.batch_size)
                      if distributed
                      else val_ds.shard_batches(self.batch_size))
            if distributed and vsteps < 1:
                # a rank's val shard would be empty: every rank must run
                # the same validation graph (the metric-average callback
                # allreduces per metric), so fall back to the full set
                val_ds = StoreDataset(
                    self.store, train_path, shard_id=0, num_shards=1,
                    chunks=list(range(n_chunks - n_val, n_chunks)))
                vsteps = val_ds.shard_batches(self.batch_size)

            def vgen():
                while True:
                    for xb, yb in val_ds.batches(self.batch_size,
                                                 limit=max(vsteps, 1)):
                        yield xb, yb

            fit_kwargs = {"validation_data": vgen(),
                          "validation_steps": max(vsteps, 1)}

        callbacks += self._store_callbacks(hvd_keras, distributed)
        self.model.fit(gen(), steps_per_epoch=steps, epochs=self.epochs,
                       initial_epoch=initial_epoch, callbacks=callbacks,
                       verbose=self.verbose, **fit_kwargs)
        if not distributed or hvd_keras.cross_rank() == 0:
            if not self.store.exists(self.checkpoint_path()):
                self.save_checkpoint()  # zero-new-epoch resumes included
        return KerasModel(self.model, self.feature_cols,
                          history=self.history)

    def _fit_multiproc_store(self) -> "KerasModel":
        """num_proc workers stream their own store shards; only the model
        bytes ride the function pickle."""
        import os
        import tempfile

        from ..elastic.discovery import FixedHosts
        from ..elastic.executor import ElasticFunctionExecutor, _serializer

        _serializer(require_by_value=True)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.keras")
            self.model.save(p)
            with open(p, "rb") as f:
                model_bytes = f.read()
        params = dict(
            batch_size=self.batch_size, epochs=self.epochs,
            feature_cols=self.feature_cols, label_cols=self.label_cols,
            run_id=self.run_id, verbose=self.verbose,
            label_dtype=self.label_dtype,
            staging_chunk_rows=self.staging_chunk_rows,
            validation=self.validation,
            resume_from_checkpoint=self.resume_from_checkpoint,
            custom_objects=self.custom_objects)
        store = self.store

        def worker(model_bytes, store, params):
            import os
            import tempfile

            import keras

            import horovod_tpu.keras as hvd_keras

            hvd_keras.init()
            with tempfile.TemporaryDirectory() as d:
                p = os.path.join(d, "model.keras")
                with open(p, "wb") as f:
                    f.write(model_bytes)
                model = keras.models.load_model(
                    p, custom_objects=params.get("custom_objects") or None)
            est = KerasEstimator(model=model, store=store, **params)
            est.fit(None)  # store path: reuses the staged chunks
            if hvd_keras.cross_rank() == 0:
                return est.model.get_weights(), est.history
            return None

        settings = ElasticFunctionExecutor.create_settings(
            min_np=self.num_proc, max_np=self.num_proc)
        ex = ElasticFunctionExecutor(
            settings, FixedHosts({"localhost": self.num_proc}),
            env_vars=dict(self.backend_env or {}))
        ex.start()
        try:
            results = ex.run(worker, args=(model_bytes, store, params))
        finally:
            ex.shutdown()
        weights, self.history = next(r for r in results if r is not None)
        self.model.set_weights(weights)
        return KerasModel(self.model, self.feature_cols,
                          history=self.history)

    def _fit_multiproc(self, x, y):
        """Launch ``num_proc`` worker processes (reference
        spark/keras/remote.py per-rank trainer): the model travels as
        ``.keras`` bytes, each worker re-compiles with the distributed
        optimizer wrap + broadcast callback and fits its shard; rank 0's
        trained weights come back to the driver model."""
        import os
        import tempfile

        from ..elastic.discovery import FixedHosts
        from ..elastic.executor import ElasticFunctionExecutor, _serializer

        _serializer(require_by_value=True)  # clear pre-flight error
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.keras")
            self.model.save(p)
            with open(p, "rb") as f:
                model_bytes = f.read()
        cfg = dict(batch_size=self.batch_size, epochs=self.epochs,
                   verbose=self.verbose,
                   validation=float(self.validation or 0.0),
                   custom_objects=self.custom_objects)

        def worker(model_bytes, x, y, cfg):
            import os
            import tempfile

            import keras

            import horovod_tpu.keras as hvd_keras

            hvd_keras.init()
            with tempfile.TemporaryDirectory() as d:
                p = os.path.join(d, "m.keras")
                with open(p, "wb") as f:
                    f.write(model_bytes)
                # load_model re-wraps the deserialized optimizer as a
                # DistributedOptimizer
                model = hvd_keras.load_model(
                    p, custom_objects=cfg["custom_objects"] or None)
            r, n = hvd_keras.cross_rank(), hvd_keras.cross_size()
            callbacks = [
                hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
                hvd_keras.callbacks.MetricAverageCallback()]
            hist = model.fit(x[r::n], y[r::n], batch_size=cfg["batch_size"],
                             epochs=cfg["epochs"], callbacks=callbacks,
                             validation_split=cfg["validation"],
                             verbose=cfg["verbose"] if r == 0 else 0)
            if r == 0:
                return model.get_weights(), {
                    k: [float(v) for v in vs]
                    for k, vs in hist.history.items()}
            return None

        settings = ElasticFunctionExecutor.create_settings(
            min_np=self.num_proc, max_np=self.num_proc)
        ex = ElasticFunctionExecutor(
            settings, FixedHosts({"localhost": self.num_proc}),
            env_vars=dict(self.backend_env))
        ex.start()
        try:
            results = ex.run(worker, args=(model_bytes, x, y, cfg))
        finally:
            ex.shutdown()
        weights, self.history = next(r for r in results if r is not None)
        self.model.set_weights(weights)
        if self.store is not None:
            self.save_checkpoint()
        return KerasModel(self.model, self.feature_cols,
                          history=self.history)


class KerasModel:
    """Transformer returned by ``fit`` (reference spark/keras/estimator.py
    KerasModel): appends prediction columns to the DataFrame. Carries the
    training ``history`` (dict of per-epoch metric lists, Keras History
    shape — reference KerasModel.getHistory)."""

    def __init__(self, model, feature_cols, output_cols=("prediction",),
                 history: Optional[dict] = None):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.output_cols = list(output_cols)
        self.history = dict(history or {})

    def getHistory(self) -> dict:
        return self.history

    def transform(self, df):
        import numpy as np

        from .common.util import (
            attach_predictions,
            dataframe_to_numpy,
            to_pandas,
        )

        pdf = to_pandas(df).copy()
        x, _ = dataframe_to_numpy(pdf, self.feature_cols)
        out = np.asarray(self.model.predict(x, verbose=0))
        return attach_predictions(pdf, out, self.output_cols)
