"""Torch Estimator for Spark-style DataFrame training.

Reference: /root/reference/horovod/spark/torch/estimator.py (TorchEstimator
→ fit(df) → TorchModel transformer) + torch/remote.py (per-rank training
loop). TPU-native slimming: data materializes through
``spark.common.util`` (pandas or pyspark DataFrames), the training loop is
plain torch on materialized arrays, and when run under the ``hvdrun``
launcher (world size > 1) gradients ride ``horovod_tpu.torch``'s
DistributedOptimizer exactly like any other torch script. Checkpoints ride
the Store abstraction (reference spark/common/store.py).
"""

from __future__ import annotations

import io
from typing import Optional, Sequence

from ..common import env as env_schema
from .common.store import Store
from .common.util import dataframe_to_numpy, train_val_split


class TorchModel:
    """Transformer returned by ``TorchEstimator.fit`` (reference
    spark/torch/estimator.py TorchModel): applies the trained model to a
    DataFrame, appending output columns. Carries the per-epoch training
    ``history`` (reference remote.py:365-380: a list of
    ``{'epoch': e, 'train': {...}, 'validation': {...}}`` dicts)."""

    def __init__(self, model, feature_cols: Sequence[str],
                 output_cols: Sequence[str] = ("prediction",),
                 history: Optional[list] = None):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.output_cols = list(output_cols)
        self.history = list(history or [])

    def getHistory(self) -> list:
        """Reference TorchModel.getHistory camelCase surface."""
        return self.history

    def transform(self, df):
        import torch

        from .common.util import attach_predictions, to_pandas

        pdf = to_pandas(df).copy()
        x, _ = dataframe_to_numpy(pdf, self.feature_cols)
        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.from_numpy(x)).numpy()
        return attach_predictions(pdf, out, self.output_cols)


class TorchEstimator:
    """Reference spark/torch/estimator.py surface: carries model /
    optimizer-factory / loss, materializes the DataFrame, trains, and
    returns a ``TorchModel``."""

    def __init__(self, num_proc: Optional[int] = None, model=None,
                 optimizer=None, loss=None,
                 feature_cols: Optional[Sequence[str]] = None,
                 label_cols: Optional[Sequence[str]] = None,
                 validation: Optional[float] = None,
                 batch_size: int = 32, epochs: int = 1,
                 store: Optional[Store] = None, run_id: str = "run0",
                 backward_passes_per_step: int = 1, verbose: int = 1,
                 backend_env: Optional[dict] = None,
                 label_dtype=None, staging_chunk_rows: int = 4096,
                 metrics: Optional[dict] = None,
                 resume_from_checkpoint: bool = False,
                 sample_weight_col: Optional[str] = None):
        self.num_proc = num_proc
        self.model = model
        self.optimizer = optimizer  # instance or factory(params)->optimizer
        self.loss = loss            # callable(output, target) -> scalar
        self.feature_cols = list(feature_cols or [])
        self.label_cols = list(label_cols or [])
        self.validation = validation
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store
        self.run_id = run_id
        self.backward_passes_per_step = backward_passes_per_step
        self.verbose = verbose
        # extra env for estimator-launched workers (e.g. JAX_PLATFORMS)
        self.backend_env = dict(backend_env or {})
        # None: integer label columns stay integer (CrossEntropy targets)
        self.label_dtype = label_dtype
        # rows per staged npz chunk on the store-backed data path
        self.staging_chunk_rows = staging_chunk_rows
        # {name: fn(outputs, labels) -> scalar} evaluated per batch and
        # averaged per epoch (reference remote.py metric_fn_groups)
        self.metrics = dict(metrics or {})
        # continue a killed run from its last per-epoch checkpoint
        # (reference estimator_params resume_from_checkpoint +
        # remote.py:141-143 state restore)
        self.resume_from_checkpoint = resume_from_checkpoint
        self.history: list = []
        # per-row weight column (reference estimator sample_weight_col;
        # remote.py calls loss_fn(outputs, labels, sample_weights)) —
        # when set, ``loss`` must accept (output, target, weight)
        self.sample_weight_col = sample_weight_col

    # -- checkpoints (Store-backed, reference spark/common/store.py) --------
    def checkpoint_path(self) -> str:
        if self.store is None:
            raise ValueError("estimator needs a store for checkpoints")
        return self.store.get_checkpoint_path(self.run_id)

    def best_checkpoint_path(self) -> str:
        return self.checkpoint_path() + ".best"

    def save_checkpoint(self, optimizer=None, epoch: Optional[int] = None,
                        path: Optional[str] = None):
        """Full training state per epoch (reference remote.py
        save_checkpoint: model + optimizer written every epoch by rank 0),
        plus the history so a resumed fit returns the COMPLETE history."""
        import torch

        state = {"model": self.model.state_dict(),
                 "optimizer": optimizer.state_dict() if optimizer else None,
                 "epoch": epoch, "history": self.history}
        buf = io.BytesIO()
        torch.save(state, buf)
        self.store.write_bytes(path or self.checkpoint_path(),
                               buf.getvalue())

    def load_checkpoint(self, optimizer=None, best: bool = False):
        """Restore model (+ optimizer when given); returns the model.
        The epoch to resume FROM lands in ``self._resume_epoch`` (0 when
        the checkpoint predates the full-state format)."""
        import torch

        path = self.best_checkpoint_path() if best else self.checkpoint_path()
        data = torch.load(io.BytesIO(self.store.read_bytes(path)))
        self._resume_epoch = 0
        if not isinstance(data, dict) or "model" not in data:
            self.model.load_state_dict(data)  # legacy raw state_dict
            return self.model
        self.model.load_state_dict(data["model"])
        if optimizer is not None and data.get("optimizer") is not None:
            optimizer.load_state_dict(data["optimizer"])
        self.history = list(data.get("history") or [])
        ep = data.get("epoch")
        self._resume_epoch = 0 if ep is None else int(ep) + 1
        return self.model

    # -- training -----------------------------------------------------------
    def _make_optimizer(self):
        import torch

        if self.optimizer is None:
            return torch.optim.SGD(self.model.parameters(), lr=0.01)
        if isinstance(self.optimizer, torch.optim.Optimizer):
            return self.optimizer
        return self.optimizer(self.model.parameters())

    def _avg_scalar(self, value_sum: float, count: int, name: str,
                    distributed: bool, hvd_torch) -> float:
        """Per-epoch metric average across ranks (role of reference
        remote.py metric_cls' allreduce): a weighted (sum, count) pair
        rides ONE sum-allreduce, so ranks with unequal batch counts —
        including an empty validation shard — contribute exactly their
        weight."""
        import torch

        if not distributed:
            return float(value_sum / count) if count else 0.0
        pair = hvd_torch.allreduce(
            torch.tensor([float(value_sum), float(count)]),
            name=f"est.metric.{name}", op=hvd_torch.Sum)
        return float(pair[0] / pair[1]) if float(pair[1]) else 0.0

    def _epoch_loop(self, opt, train_batches, val_batches, distributed,
                    hvd_torch, raw_opt=None) -> list:
        """Reference spark/torch/remote.py:313-385 loop shape: per epoch —
        train pass (loss + user metrics, rank-averaged), validation pass,
        history append, rank-0 per-epoch checkpoint with best-model
        tracking, and resume from the last checkpoint when asked.

        ``train_batches(epoch)`` / ``val_batches()`` yield (xb, yb) torch
        tensors; ``raw_opt`` is the unwrapped optimizer whose state_dict
        rides the checkpoint (the Distributed wrapper shares it).
        """
        import logging

        import torch

        log = logging.getLogger("horovod_tpu")
        rank0 = (not distributed) or hvd_torch.cross_rank() == 0
        start_epoch = 0
        self.history = []
        ckpt_opt = raw_opt if raw_opt is not None else opt
        if (self.resume_from_checkpoint and self.store is not None
                and self.store.exists(self.checkpoint_path())):
            self.load_checkpoint(optimizer=ckpt_opt)
            start_epoch = self._resume_epoch
            if self.verbose and rank0:
                log.info("TorchEstimator resuming run %s from epoch %d",
                         self.run_id, start_epoch)
        if distributed:
            # resume included: rank 0's restored weights win everywhere
            hvd_torch.broadcast_parameters(self.model.state_dict(),
                                           root_rank=0)
        best_val = min(
            (h.get("validation", {}).get("loss", float("inf"))
             for h in self.history), default=float("inf"))

        def run_pass(batch_iter, train: bool, epoch: int) -> dict:
            total, steps = 0.0, 0
            msums = {name: 0.0 for name in self.metrics}
            for batch in batch_iter:
                xb, yb, *rest = batch
                wb = rest[0] if rest else None

                def compute_loss(out):
                    # reference remote.py:398 train_minibatch calls
                    # loss_fn(outputs, labels, sample_weights)
                    if wb is None:
                        return self.loss(out, yb)
                    return self.loss(out, yb, wb)

                if train:
                    opt.zero_grad()
                    out = self.model(xb)
                    loss = compute_loss(out)
                    loss.backward()
                    opt.step()
                else:
                    with torch.no_grad():
                        out = self.model(xb)
                        loss = compute_loss(out)
                total += float(loss.detach())
                for name, fn in self.metrics.items():
                    with torch.no_grad():
                        msums[name] += float(fn(out.detach(), yb))
                steps += 1
            stage = "train" if train else "val"
            result = {"loss": self._avg_scalar(
                total, steps, f"{stage}.loss.{epoch}", distributed,
                hvd_torch)}
            for name in self.metrics:
                result[name] = self._avg_scalar(
                    msums[name], steps, f"{stage}.{name}.{epoch}",
                    distributed, hvd_torch)
            return result

        for epoch in range(start_epoch, self.epochs):
            self.model.train()
            entry = {"epoch": epoch,
                     "train": run_pass(train_batches(epoch), True, epoch)}
            vb = val_batches() if val_batches is not None else None
            if vb is not None:
                self.model.eval()
                entry["validation"] = run_pass(vb, False, epoch)
                self.model.train()
            self.history.append(entry)
            if self.verbose and rank0:
                log.info("TorchEstimator %s", entry)
            if self.store is not None and rank0:
                # per-epoch checkpoint + best-model tracking (reference
                # saves every epoch; best is kept separately so a
                # regression in late epochs cannot lose the best weights)
                self.save_checkpoint(optimizer=ckpt_opt, epoch=epoch)
                score = entry.get("validation", entry["train"])["loss"]
                if score <= best_val:
                    best_val = score
                    self.save_checkpoint(optimizer=ckpt_opt, epoch=epoch,
                                         path=self.best_checkpoint_path())
        return self.history

    def fit(self, df) -> TorchModel:
        """Train on a pandas (hermetic) or pyspark DataFrame. Under a
        multi-process launch (``hvd.size() > 1`` after init) gradients are
        allreduced via the torch shim's DistributedOptimizer; standalone it
        is a plain local loop — same contract as the reference's remote
        trainer running on one executor."""
        import numpy as np
        import torch

        import os

        if self.model is None or not self.feature_cols or not self.label_cols:
            raise ValueError("model, feature_cols and label_cols are required")
        if self.loss is None:
            raise ValueError(
                "TorchEstimator requires loss= (silently defaulting to MSE "
                "would train a classifier on the wrong objective)")
        if self.store is not None:
            # store-backed path: stage through the Store, stream per-rank
            # chunks — the dataset is never materialized whole (reference
            # spark/common/util.py:747 prepare_data + petastorm readers)
            if self.sample_weight_col:
                raise ValueError(
                    "sample_weight_col is supported on the in-memory "
                    "(pandas) path; the store staging format carries "
                    "features+labels only")
            return self._fit_from_store(df)
        from .common.util import to_pandas

        if (self.sample_weight_col and self.num_proc and self.num_proc > 1
                and env_schema.HOROVOD_RANK not in os.environ):
            # fail BEFORE the driver-side collect (all inputs to this
            # check are known already; collecting GBs first would waste
            # the most expensive step)
            raise ValueError(
                "sample_weight_col with estimator-launched num_proc "
                "is not supported; launch the workers with hvdrun "
                "instead (the launcher-distributed path shards the "
                "weights with the data)")
        # collect ONCE: a second toPandas() of an unordered pyspark plan
        # could return rows in a different order and silently misalign
        # the weights with their features
        pdf = to_pandas(df)
        x, y = dataframe_to_numpy(pdf, self.feature_cols, self.label_cols,
                                  label_dtype=self.label_dtype)
        w = None
        if self.sample_weight_col:
            w = pdf[self.sample_weight_col].to_numpy(np.float32)
        (x, y), (x_val, y_val) = train_val_split(x, y, self.validation)
        (w, _), (w_val, _) = train_val_split(w, None, self.validation) \
            if w is not None else ((None, None), (None, None))
        if (self.num_proc and self.num_proc > 1
                and env_schema.HOROVOD_RANK not in os.environ):
            # estimator-launched distributed fit: spawn num_proc worker
            # processes (the reference estimator launches
            # horovod.spark.run the same way); each worker re-enters this
            # method with a live hvd world and takes the sharded branch
            # (sample_weight_col was rejected before the collect above)
            return self._fit_multiproc(x, y, x_val, y_val)
        opt = self._make_optimizer()
        import horovod_tpu.torch as hvd_torch

        # the torch shim's data-parallel/allreduce unit is the *process*
        # (eager collectives reduce across processes; chips within a
        # process are one worker), so sharding gates on cross_size
        distributed = False
        try:
            if hvd_torch.is_initialized() and hvd_torch.cross_size() > 1:
                distributed = True
        except Exception:
            distributed = False
        if distributed:
            # (no broadcast here: _epoch_loop broadcasts after its resume
            # check, which must win over initial weights)
            opt = hvd_torch.DistributedOptimizer(
                opt, named_parameters=self.model.named_parameters(),
                backward_passes_per_step=self.backward_passes_per_step)

        xt = torch.from_numpy(np.ascontiguousarray(x))
        yt = torch.from_numpy(np.ascontiguousarray(y))
        wt = (torch.from_numpy(np.ascontiguousarray(w))
              if w is not None else None)
        if distributed:
            # each process trains its shard (reference: petastorm
            # row-group sharding per rank)
            r, n = hvd_torch.cross_rank(), hvd_torch.cross_size()
            xt, yt = xt[r::n], yt[r::n]
            wt = wt[r::n] if wt is not None else None

        def train_batches(epoch):
            gen = torch.Generator().manual_seed(epoch)
            perm = torch.randperm(len(xt), generator=gen)
            for i in range(0, len(xt), self.batch_size):
                idx = perm[i:i + self.batch_size]
                if wt is None:
                    yield xt[idx], yt[idx]
                else:
                    yield xt[idx], yt[idx], wt[idx]

        val_batches = None
        if x_val is not None:
            xv = torch.from_numpy(np.ascontiguousarray(x_val))
            yv = torch.from_numpy(np.ascontiguousarray(y_val))
            wv = (torch.from_numpy(np.ascontiguousarray(w_val))
                  if w is not None and w_val is not None else None)

            def val_batches():
                for i in range(0, len(xv), self.batch_size):
                    sl = slice(i, i + self.batch_size)
                    if wv is None:
                        yield xv[sl], yv[sl]
                    else:
                        yield xv[sl], yv[sl], wv[sl]

        self._epoch_loop(opt, train_batches, val_batches, distributed,
                         hvd_torch)
        return TorchModel(self.model, self.feature_cols,
                          history=self.history)

    # -- store-backed streaming path (reference util.py:747 + petastorm) ----
    def _fit_from_store(self, df) -> TorchModel:
        import os

        from .common.datamodule import (StoreDataset, load_meta, meta_path,
                                        stage_dataframe)

        train_path = self.store.get_train_data_path()
        if df is not None:
            # stage once on the driver; worker re-entry passes df=None and
            # reuses the staged chunks (reference prepare_data caches by
            # dataset index — here one staged dataset per store prefix).
            # The validation split reserves whole tail chunks, so cap the
            # chunk size to the validation row budget when it is known.
            chunk_rows = self.staging_chunk_rows
            if self.validation and hasattr(df, "__len__"):
                chunk_rows = min(chunk_rows, max(
                    1, int(len(df) * float(self.validation))))
            stage_dataframe(df, self.store, train_path, self.feature_cols,
                            self.label_cols, label_dtype=self.label_dtype,
                            chunk_rows=chunk_rows)
        elif not self.store.exists(meta_path(train_path)):
            raise ValueError("no staged dataset in the store and no "
                             "DataFrame to stage")
        if (self.num_proc and self.num_proc > 1
                and env_schema.HOROVOD_RANK not in os.environ):
            return self._fit_multiproc_store()

        import horovod_tpu.torch as hvd_torch

        try:
            distributed = (hvd_torch.is_initialized()
                           and hvd_torch.cross_size() > 1)
        except Exception:
            distributed = False
        r = hvd_torch.cross_rank() if distributed else 0
        n = hvd_torch.cross_size() if distributed else 1
        n_chunks = load_meta(self.store, train_path)["n_chunks"]
        n_val = 0
        if self.validation:
            if n_chunks < 2:
                raise ValueError(
                    "validation split on the store path reserves whole "
                    "chunks; stage at least 2 chunks (lower "
                    "staging_chunk_rows)")
            n_val = max(1, round(float(self.validation) * n_chunks))
            n_val = min(n_val, n_chunks - 1)
        train_chunks = list(range(n_chunks - n_val))
        ds = StoreDataset(self.store, train_path, shard_id=r, num_shards=n,
                          chunks=train_chunks)
        # validation shards across ranks too: the epoch metric is the
        # allreduce-average of shard means, so each rank reading 1/n of
        # the val chunks gives the same number at 1/n the IO
        val_ds = (StoreDataset(self.store, train_path, shard_id=r,
                               num_shards=n,
                               chunks=list(range(n_chunks - n_val, n_chunks)))
                  if n_val else None)
        return self._train_streaming(ds, val_ds, distributed)

    def _train_streaming(self, ds, val_ds, distributed: bool) -> TorchModel:
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd_torch

        opt = self._make_optimizer()
        if distributed:
            # (no broadcast here: _epoch_loop broadcasts after its resume
            # check, which must win over initial weights)
            opt = hvd_torch.DistributedOptimizer(
                opt, named_parameters=self.model.named_parameters(),
                backward_passes_per_step=self.backward_passes_per_step)
        # symmetric step count: every rank must run the same number of
        # optimizer steps per epoch (each step allreduces); computed from
        # staged metadata alone, no negotiation round. Tail batches beyond
        # the smallest shard are skipped (documented).
        limit = (ds.min_shard_batches(self.batch_size) if distributed
                 else None)
        if (limit == 0) or (not distributed and len(ds) == 0):
            raise ValueError(
                "staged dataset has no rows for some shard — zero optimizer "
                "steps would silently train nothing (restage with smaller "
                "staging_chunk_rows or fewer workers)")
        self.last_train_dataset = ds  # observability (tests assert the
        #                               streaming property on it)

        def tt(a):
            return torch.from_numpy(np.ascontiguousarray(a))

        def train_batches(epoch):
            for xb, yb in ds.batches(self.batch_size, shuffle_seed=epoch,
                                     limit=limit):
                yield tt(xb), tt(yb)

        val_batches = None
        if val_ds is not None:
            def val_batches():
                for xb, yb in val_ds.batches(self.batch_size):
                    yield tt(xb), tt(yb)

        self._epoch_loop(opt, train_batches, val_batches, distributed,
                         hvd_torch)
        return TorchModel(self.model, self.feature_cols,
                          history=self.history)

    def _fit_multiproc_store(self) -> TorchModel:
        """num_proc workers stream their own store shards — no dataset
        bytes ride the function pickle (reference: executors read their
        petastorm shard straight from the store)."""
        from ..elastic.discovery import FixedHosts
        from ..elastic.executor import ElasticFunctionExecutor, _serializer

        _serializer(require_by_value=True)

        def worker(est):
            import horovod_tpu

            horovod_tpu.init()
            import horovod_tpu.torch as hvd_torch

            est.fit(None)  # store path: reuses the staged chunks
            if hvd_torch.cross_rank() == 0:
                return ({k: v.cpu()
                         for k, v in est.model.state_dict().items()},
                        est.history)
            return None

        settings = ElasticFunctionExecutor.create_settings(
            min_np=self.num_proc, max_np=self.num_proc)
        ex = ElasticFunctionExecutor(
            settings, FixedHosts({"localhost": self.num_proc}),
            env_vars=dict(self.backend_env or {}))
        ex.start()
        try:
            results = ex.run(worker, args=(self,))
        finally:
            ex.shutdown()
        state, self.history = next(r for r in results if r is not None)
        self.model.load_state_dict(state)
        return TorchModel(self.model, self.feature_cols,
                          history=self.history)

    def _log_validation(self, x_val, y_val):
        if x_val is None or not self.verbose:
            return
        import logging

        import torch

        self.model.eval()
        with torch.no_grad():
            vl = float(self.loss(self.model(torch.from_numpy(x_val)),
                                 torch.from_numpy(y_val)))
        logging.getLogger("horovod_tpu").info(
            "TorchEstimator validation loss %.5f", vl)

    def _fit_multiproc(self, x, y, x_val, y_val):
        """Launch ``num_proc`` local worker processes through the shared
        elastic function executor; workers train the sharded loop with
        gradients allreduced by the torch shim, rank 0 returns the trained
        state_dict (reference spark/torch/remote.py's per-rank trainer +
        driver-side model collection)."""
        import pandas as pd

        from ..elastic.discovery import FixedHosts
        from ..elastic.executor import ElasticFunctionExecutor, _serializer

        _serializer(require_by_value=True)  # clear pre-flight error

        est = TorchEstimator(
            model=self.model, optimizer=self.optimizer, loss=self.loss,
            feature_cols=["__f"], label_cols=["__y"],
            metrics=self.metrics,  # x/y arrive pre-split: no re-split here
            batch_size=self.batch_size, epochs=self.epochs,
            backward_passes_per_step=self.backward_passes_per_step,
            verbose=self.verbose)

        def worker(est, x, y):
            import horovod_tpu

            horovod_tpu.init()
            import horovod_tpu.torch as hvd_torch

            df = pd.DataFrame({"__f": list(x), "__y": list(y)})
            est.fit(df)
            if hvd_torch.cross_rank() == 0:
                return ({k: v.cpu()
                         for k, v in est.model.state_dict().items()},
                        est.history)
            return None

        settings = ElasticFunctionExecutor.create_settings(
            min_np=self.num_proc, max_np=self.num_proc)
        ex = ElasticFunctionExecutor(
            settings, FixedHosts({"localhost": self.num_proc}),
            env_vars=dict(self.backend_env or {}))
        ex.start()
        try:
            results = ex.run(worker, args=(est, x, y))
        finally:
            ex.shutdown()
        state, self.history = next(r for r in results if r is not None)
        self.model.load_state_dict(state)
        self._log_validation(x_val, y_val)
        if self.store is not None:
            self.save_checkpoint()
        return TorchModel(self.model, self.feature_cols,
                          history=self.history)
