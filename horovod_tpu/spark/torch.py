"""Torch Estimator for Spark-style DataFrame training.

Reference: /root/reference/horovod/spark/torch/estimator.py (TorchEstimator
→ fit(df) → TorchModel transformer) + torch/remote.py (per-rank training
loop). TPU-native slimming: data materializes through
``spark.common.util`` (pandas or pyspark DataFrames), the training loop is
plain torch on materialized arrays, and when run under the ``hvdrun``
launcher (world size > 1) gradients ride ``horovod_tpu.torch``'s
DistributedOptimizer exactly like any other torch script. Checkpoints ride
the Store abstraction (reference spark/common/store.py).
"""

from __future__ import annotations

import io
from typing import Callable, Optional, Sequence

from .common.store import Store
from .common.util import dataframe_to_numpy, train_val_split


class TorchModel:
    """Transformer returned by ``TorchEstimator.fit`` (reference
    spark/torch/estimator.py TorchModel): applies the trained model to a
    DataFrame, appending output columns."""

    def __init__(self, model, feature_cols: Sequence[str],
                 output_cols: Sequence[str] = ("prediction",)):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.output_cols = list(output_cols)

    def transform(self, df):
        import torch

        from .common.util import attach_predictions, to_pandas

        pdf = to_pandas(df).copy()
        x, _ = dataframe_to_numpy(pdf, self.feature_cols)
        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.from_numpy(x)).numpy()
        return attach_predictions(pdf, out, self.output_cols)


class TorchEstimator:
    """Reference spark/torch/estimator.py surface: carries model /
    optimizer-factory / loss, materializes the DataFrame, trains, and
    returns a ``TorchModel``."""

    def __init__(self, num_proc: Optional[int] = None, model=None,
                 optimizer=None, loss=None,
                 feature_cols: Optional[Sequence[str]] = None,
                 label_cols: Optional[Sequence[str]] = None,
                 validation: Optional[float] = None,
                 batch_size: int = 32, epochs: int = 1,
                 store: Optional[Store] = None, run_id: str = "run0",
                 backward_passes_per_step: int = 1, verbose: int = 1,
                 backend_env: Optional[dict] = None):
        self.num_proc = num_proc
        self.model = model
        self.optimizer = optimizer  # instance or factory(params)->optimizer
        self.loss = loss            # callable(output, target) -> scalar
        self.feature_cols = list(feature_cols or [])
        self.label_cols = list(label_cols or [])
        self.validation = validation
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store
        self.run_id = run_id
        self.backward_passes_per_step = backward_passes_per_step
        self.verbose = verbose
        # extra env for estimator-launched workers (e.g. JAX_PLATFORMS)
        self.backend_env = dict(backend_env or {})

    # -- checkpoints (Store-backed, reference spark/common/store.py) --------
    def checkpoint_path(self) -> str:
        if self.store is None:
            raise ValueError("estimator needs a store for checkpoints")
        return self.store.get_checkpoint_path(self.run_id)

    def save_checkpoint(self):
        import torch

        buf = io.BytesIO()
        torch.save(self.model.state_dict(), buf)
        self.store.write_bytes(self.checkpoint_path(), buf.getvalue())

    def load_checkpoint(self):
        import torch

        data = self.store.read_bytes(self.checkpoint_path())
        self.model.load_state_dict(torch.load(io.BytesIO(data)))
        return self.model

    # -- training -----------------------------------------------------------
    def _make_optimizer(self):
        import torch

        if self.optimizer is None:
            return torch.optim.SGD(self.model.parameters(), lr=0.01)
        if isinstance(self.optimizer, torch.optim.Optimizer):
            return self.optimizer
        return self.optimizer(self.model.parameters())

    def fit(self, df) -> TorchModel:
        """Train on a pandas (hermetic) or pyspark DataFrame. Under a
        multi-process launch (``hvd.size() > 1`` after init) gradients are
        allreduced via the torch shim's DistributedOptimizer; standalone it
        is a plain local loop — same contract as the reference's remote
        trainer running on one executor."""
        import numpy as np
        import torch

        import os

        if self.model is None or not self.feature_cols or not self.label_cols:
            raise ValueError("model, feature_cols and label_cols are required")
        x, y = dataframe_to_numpy(df, self.feature_cols, self.label_cols)
        (x, y), (x_val, y_val) = train_val_split(x, y, self.validation)

        if self.loss is None:
            raise ValueError(
                "TorchEstimator requires loss= (silently defaulting to MSE "
                "would train a classifier on the wrong objective)")
        if (self.num_proc and self.num_proc > 1
                and "HOROVOD_RANK" not in os.environ):
            # estimator-launched distributed fit: spawn num_proc worker
            # processes (the reference estimator launches
            # horovod.spark.run the same way); each worker re-enters this
            # method with a live hvd world and takes the sharded branch
            return self._fit_multiproc(x, y, x_val, y_val)
        opt = self._make_optimizer()
        import horovod_tpu.torch as hvd_torch

        # the torch shim's data-parallel/allreduce unit is the *process*
        # (eager collectives reduce across processes; chips within a
        # process are one worker), so sharding gates on cross_size
        distributed = False
        try:
            if hvd_torch.is_initialized() and hvd_torch.cross_size() > 1:
                distributed = True
        except Exception:
            distributed = False
        if distributed:
            opt = hvd_torch.DistributedOptimizer(
                opt, named_parameters=self.model.named_parameters(),
                backward_passes_per_step=self.backward_passes_per_step)
            hvd_torch.broadcast_parameters(self.model.state_dict(),
                                           root_rank=0)

        loss_fn = self.loss
        xt = torch.from_numpy(np.ascontiguousarray(x))
        yt = torch.from_numpy(np.ascontiguousarray(y))
        if distributed:
            # each process trains its shard (reference: petastorm
            # row-group sharding per rank)
            r, n = hvd_torch.cross_rank(), hvd_torch.cross_size()
            xt, yt = xt[r::n], yt[r::n]
        self.model.train()
        for epoch in range(self.epochs):
            perm = torch.randperm(len(xt))
            total = 0.0
            for i in range(0, len(xt), self.batch_size):
                idx = perm[i:i + self.batch_size]
                opt.zero_grad()
                out = self.model(xt[idx])
                loss = loss_fn(out, yt[idx])
                loss.backward()
                opt.step()
                total += float(loss.detach())
            if self.verbose:
                import logging

                logging.getLogger("horovod_tpu").info(
                    "TorchEstimator epoch %d loss %.5f", epoch, total)
        self._log_validation(x_val, y_val)
        if self.store is not None and (not distributed
                                       or hvd_torch.cross_rank() == 0):
            self.save_checkpoint()
        return TorchModel(self.model, self.feature_cols)

    def _log_validation(self, x_val, y_val):
        if x_val is None or not self.verbose:
            return
        import logging

        import torch

        self.model.eval()
        with torch.no_grad():
            vl = float(self.loss(self.model(torch.from_numpy(x_val)),
                                 torch.from_numpy(y_val)))
        logging.getLogger("horovod_tpu").info(
            "TorchEstimator validation loss %.5f", vl)

    def _fit_multiproc(self, x, y, x_val, y_val):
        """Launch ``num_proc`` local worker processes through the shared
        elastic function executor; workers train the sharded loop with
        gradients allreduced by the torch shim, rank 0 returns the trained
        state_dict (reference spark/torch/remote.py's per-rank trainer +
        driver-side model collection)."""
        import pandas as pd

        from ..elastic.discovery import FixedHosts
        from ..elastic.executor import ElasticFunctionExecutor, _serializer

        _serializer(require_by_value=True)  # clear pre-flight error

        est = TorchEstimator(
            model=self.model, optimizer=self.optimizer, loss=self.loss,
            feature_cols=["__f"], label_cols=["__y"],
            batch_size=self.batch_size, epochs=self.epochs,
            backward_passes_per_step=self.backward_passes_per_step,
            verbose=self.verbose)

        def worker(est, x, y):
            import horovod_tpu

            horovod_tpu.init()
            import horovod_tpu.torch as hvd_torch

            df = pd.DataFrame({"__f": list(x), "__y": list(y)})
            est.fit(df)
            if hvd_torch.cross_rank() == 0:
                return {k: v.cpu() for k, v in est.model.state_dict().items()}
            return None

        settings = ElasticFunctionExecutor.create_settings(
            min_np=self.num_proc, max_np=self.num_proc)
        ex = ElasticFunctionExecutor(
            settings, FixedHosts({"localhost": self.num_proc}),
            env_vars=dict(self.backend_env or {}))
        ex.start()
        try:
            results = ex.run(worker, args=(est, x, y))
        finally:
            ex.shutdown()
        state = next(r for r in results if r is not None)
        self.model.load_state_dict(state)
        self._log_validation(x_val, y_val)
        if self.store is not None:
            self.save_checkpoint()
        return TorchModel(self.model, self.feature_cols)
