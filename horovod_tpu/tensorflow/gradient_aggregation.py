"""Local gradient aggregation for ``backward_passes_per_step > 1`` on the
legacy ``tf.compat.v1.train.Optimizer`` path.

Reference: /root/reference/horovod/tensorflow/gradient_aggregation.py:16
(LocalGradientAggregationHelper) — a graph-mode machine of shadow
variables, ``tf.cond`` ladders and control dependencies, because v1 graphs
trace once and replay. This shim executes eagerly (the numpy bridge needs
concrete tensors), so the redesign is a plain eager accumulator with the
same semantics:

- gradients accumulate locally for ``backward_passes_per_step`` calls;
- the cross-process allreduce happens only on the window's last call
  (optionally dividing by the window length —
  ``average_aggregated_gradients``);
- ``apply_gradients`` actually applies only on those boundary calls, and
  otherwise just advances the tracked global step, exactly like the
  reference's cond ladder (gradient_aggregation.py:232-268).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import tensorflow as tf


class LocalGradientAggregationHelper:
    def __init__(self, backward_passes_per_step: int,
                 allreduce_func: Callable[[List], List],
                 sparse_as_dense: bool = False,
                 average_aggregated_gradients: bool = False):
        if backward_passes_per_step <= 0:
            raise ValueError("backward_passes_per_step must be > 0")
        self.backward_passes_per_step = int(backward_passes_per_step)
        self._allreduce = allreduce_func
        self.sparse_as_dense = sparse_as_dense
        self.average_aggregated_gradients = average_aggregated_gradients
        # counter == 0 means "a window just closed" (or nothing ran yet):
        # the next compute starts a fresh window, and apply may proceed
        self.counter = 0
        self._agg: Optional[list] = None

    def _densify(self, grads: list) -> list:
        out = []
        for g in grads:
            if isinstance(g, tf.IndexedSlices):
                if not self.sparse_as_dense:
                    raise ValueError(
                        "IndexedSlices are not supported when "
                        "backward_passes_per_step > 1 and sparse_as_dense "
                        "is False (reference gradient_aggregation.py:83-88)")
                g = tf.convert_to_tensor(g)
            out.append(g)
        return out

    @staticmethod
    def _require_eager(what: str):
        """This helper's counter and branching are Python state: traced
        into a tf.function or a v1 Session graph they would bake in one
        branch and silently freeze training. The whole numpy-bridge shim
        is eager-execution; fail loudly rather than train nothing."""
        if not tf.executing_eagerly():
            raise NotImplementedError(
                f"{what} with backward_passes_per_step > 1 runs eagerly "
                "only (the horovod_tpu TF shim stages tensors through "
                "numpy); call it outside tf.function / Session graphs")

    def compute_gradients(self, grads: list) -> list:
        """Accumulate; on the window's last call return the allreduced
        aggregate (reference compute_gradients,
        gradient_aggregation.py:175-228). Off-boundary returns the raw
        local grads — which apply_gradients will skip."""
        self._require_eager("compute_gradients")
        grads = self._densify(grads)
        if self.counter == 0:
            self._agg = [None if g is None else tf.zeros_like(g)
                         for g in grads]
        if len(grads) != len(self._agg):
            raise ValueError(
                f"gradient count changed mid-window: {len(self._agg)} -> "
                f"{len(grads)}")
        # a slot can be None on the window's first pass and real later
        # (conditionally-active branches): seed it from the first real grad
        self._agg = [a if g is None else (g if a is None else a + g)
                     for a, g in zip(self._agg, grads)]
        self.counter += 1
        if self.counter < self.backward_passes_per_step:
            return grads
        self.counter = 0
        reduced = self._allreduce(self._agg)
        self._agg = None
        if self.average_aggregated_gradients:
            reduced = [None if g is None
                       else g / float(self.backward_passes_per_step)
                       for g in reduced]
        return reduced

    @property
    def at_boundary(self) -> bool:
        """True right after a window closed: apply_gradients may proceed."""
        return self.counter == 0

    def apply_gradients(self, apply_closure: Callable,
                        global_step: Optional[tf.Variable] = None):
        """Run ``apply_closure`` only on boundary steps; otherwise advance
        the tracked global step so step-count-driven schedules stay
        monotonic (reference apply_gradients cond ladder,
        gradient_aggregation.py:232-268)."""
        self._require_eager("apply_gradients")
        if self.at_boundary:
            return apply_closure()
        if global_step is not None:
            global_step.assign_add(1)
        return tf.no_op()
