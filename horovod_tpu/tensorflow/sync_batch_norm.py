"""Cross-worker synchronized BatchNormalization for TF/Keras models.

Reference: /root/reference/horovod/tensorflow/sync_batch_norm.py — batch
statistics are averaged across all workers each step (crucial for small
per-worker batches). Implemented as a standalone Keras layer (Keras 3's
BatchNormalization internals are not a stable override surface): local
mean / mean-of-squares are allreduce-averaged through the eager runtime
via ``tf.py_function`` so it also works under ``tf.function`` tracing.
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

import horovod_tpu as _core


class SyncBatchNormalization(tf.keras.layers.Layer):
    def __init__(self, axis: int = -1, momentum: float = 0.99,
                 epsilon: float = 1e-3, name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.axis = axis
        self.momentum = momentum
        self.epsilon = epsilon

    def build(self, input_shape):
        dim = int(input_shape[self.axis])
        self.gamma = self.add_weight(name="gamma", shape=(dim,),
                                     initializer="ones", trainable=True)
        self.beta = self.add_weight(name="beta", shape=(dim,),
                                    initializer="zeros", trainable=True)
        self.moving_mean = self.add_weight(
            name="moving_mean", shape=(dim,), initializer="zeros",
            trainable=False)
        self.moving_variance = self.add_weight(
            name="moving_variance", shape=(dim,), initializer="ones",
            trainable=False)
        super().build(input_shape)

    @staticmethod
    def _global_moments(mean, meansq):
        """Average local [mean, mean-of-squares] across workers (reference
        sync_batch_norm.py's allreduce of statistics)."""
        if _core.cross_size() <= 1:
            return mean, meansq

        def _reduce(m, ms):
            stacked = np.stack([m.numpy(), ms.numpy()])
            out = _core.synchronize(_core.allreduce_async(
                stacked, average=True, name="sync_bn.moments"))
            out = np.asarray(out)
            return out[0].astype(np.float32), out[1].astype(np.float32)

        gm, gms = tf.py_function(_reduce, [mean, meansq],
                                 [tf.float32, tf.float32])
        gm.set_shape(mean.shape)
        gms.set_shape(meansq.shape)
        return tf.cast(gm, mean.dtype), tf.cast(gms, meansq.dtype)

    def call(self, inputs, training=False):
        reduce_axes = [i for i in range(inputs.shape.rank)
                       if i != (self.axis % inputs.shape.rank)]
        if training:
            mean = tf.reduce_mean(inputs, axis=reduce_axes)
            meansq = tf.reduce_mean(tf.square(inputs), axis=reduce_axes)
            mean, meansq = self._global_moments(mean, meansq)
            var = meansq - tf.square(mean)
            self.moving_mean.assign(
                self.momentum * self.moving_mean + (1 - self.momentum) * mean)
            self.moving_variance.assign(
                self.momentum * self.moving_variance
                + (1 - self.momentum) * var)
        else:
            mean, var = self.moving_mean, self.moving_variance
        return tf.nn.batch_normalization(
            inputs, mean, var, self.beta, self.gamma, self.epsilon)
