"""Gradient compression for the TensorFlow API.

Reference: /root/reference/horovod/tensorflow/compression.py — a
`Compressor` with ``none``/``fp16`` cast-on-the-wire implementations. Here
``bf16`` is added as the TPU-native 16-bit format (MXU-consumable).
"""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = tf.float16

    @classmethod
    def compress(cls, tensor):
        if tensor.dtype.is_floating and tensor.dtype != cls.wire_dtype:
            return tf.cast(tensor, cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = tf.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = tf.bfloat16


def _quant_marker(bits: int):
    """The shared blockwise-quantized wire markers (ops/compression.py):
    compress/decompress are identity on the TF side — the runtime
    compiles the quantization into the fused chunk programs and the
    marker's ``quant_spec`` is what the collective paths read."""
    from ..ops.compression import Compression as _CoreCompression

    return _CoreCompression.int8 if bits == 8 else _CoreCompression.int4


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = _quant_marker(8)
    int4 = _quant_marker(4)
