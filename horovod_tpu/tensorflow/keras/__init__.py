"""horovod_tpu.tensorflow.keras — tf.keras-facing API (reference
horovod/tensorflow/keras/__init__.py); shares the implementation with
horovod_tpu.keras (both front Keras 3)."""

from horovod_tpu.keras import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    DistributedOptimizer,
    Sum,
    allgather_object,
    broadcast_object,
    broadcast_variables,
    elastic,
    ccl_built,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    is_homogeneous,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    start_timeline,
    stop_timeline,
    tpu_built,
    tpu_enabled,
    init,
    is_initialized,
    load_model,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from horovod_tpu.tensorflow.elastic import (  # noqa: F401
    TensorFlowKerasState,
    TensorFlowState,
)

# callbacks must subclass the generation tf.keras actually resolves to:
# Keras 3 normally, tf_keras under TF_USE_LEGACY_KERAS=1 (the reference
# era's API — a Keras-3 Callback handed to tf_keras's fit fails its
# callback-list introspection)
import tensorflow as _tf  # noqa: E402

from horovod_tpu._keras.callbacks import for_backend as _cb_for_backend  # noqa: E402

callbacks = _cb_for_backend(_tf.keras)

# hvd.elastic under this namespace gets the SAME backend treatment: its
# CommitState/UpdateBatchState callbacks must subclass tf.keras's
# generation too, while KerasState/run are generation-neutral
from horovod_tpu.common.util import module_namespace as _module_ns  # noqa: E402
from horovod_tpu.keras import elastic as _elastic_mod  # noqa: E402

elastic = _module_ns(
    _elastic_mod,
    CommitStateCallback=callbacks.CommitStateCallback,
    UpdateBatchStateCallback=callbacks.UpdateBatchStateCallback)
