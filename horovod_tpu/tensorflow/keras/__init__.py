"""horovod_tpu.tensorflow.keras — tf.keras-facing API (reference
horovod/tensorflow/keras/__init__.py); shares the implementation with
horovod_tpu.keras (both front Keras 3)."""

from horovod_tpu.keras import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    DistributedOptimizer,
    Sum,
    allgather_object,
    broadcast_object,
    broadcast_variables,
    callbacks,
    elastic,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    load_model,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from horovod_tpu.tensorflow.elastic import (  # noqa: F401
    TensorFlowKerasState,
    TensorFlowState,
)
