"""Elastic state handlers for TF/Keras (reference
horovod/tensorflow/elastic.py — TensorFlowState/TensorFlowKerasState):
snapshot/restore/broadcast of variables so elastic restarts resume from
committed state.
"""

from __future__ import annotations

import numpy as np

import horovod_tpu as _core
from horovod_tpu.elastic.state import ObjectState

from .functions import broadcast_variables


class TensorFlowState(ObjectState):
    """Tracks a list of tf.Variables (reference elastic.py TensorFlowState).
    commit() snapshots values host-side; restore() assigns them back;
    sync() broadcasts from rank 0."""

    def __init__(self, variables=None, **kwargs):
        self._variables = list(variables or [])
        self._tf_saved = None
        super().__init__(**kwargs)

    def save(self):
        self._tf_saved = [np.asarray(v.numpy()) for v in self._variables]
        super().save()

    def restore(self):
        if self._tf_saved is not None:
            for v, s in zip(self._variables, self._tf_saved):
                v.assign(s)
        super().restore()

    def sync(self):
        if self._variables and _core.cross_size() > 1:
            broadcast_variables(self._variables, root_rank=0)
        super().sync()


class TensorFlowKerasState(TensorFlowState):
    """Model+optimizer variant (reference TensorFlowKerasState).

    The tracked variable list is RE-COLLECTED at every save()/sync():
    Keras creates optimizer slot variables (momentum, Adam moments)
    lazily at the first apply step, and a list frozen at construction
    would silently exclude them from snapshots and broadcasts."""

    def __init__(self, model, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer or getattr(model, "optimizer", None)
        super().__init__(variables=None, **kwargs)

    def _collect(self):
        variables = list(self.model.variables)
        if self.optimizer is not None:
            ovars = getattr(self.optimizer, "variables", None)
            if callable(ovars) and not hasattr(ovars, "__iter__"):
                ovars = ovars()  # Keras-2 optimizer_v2: variables() method
            variables += list(ovars or [])
        return variables

    def save(self):
        self._variables = self._collect()
        super().save()

    def sync(self):
        self._variables = self._collect()
        super().sync()
