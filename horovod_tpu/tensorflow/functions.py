"""TF variable/object broadcast helpers.

Reference: /root/reference/horovod/tensorflow/functions.py —
``broadcast_variables`` (:47), ``broadcast_object``/``broadcast_object_fn``
and ``allgather_object``. Variables are assigned in place from the
root's values; objects ride the core's pickle-based collectives.
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

import horovod_tpu as _core


def broadcast_variables(variables, root_rank: int = 0,
                        process_set=None, inplace: bool = False):
    """Assign every variable the root rank's value (reference
    functions.py:47). Called once after init / checkpoint restore so all
    workers start identically."""
    if not tf.executing_eagerly():
        # under tf.function (the reference example broadcasts inside the
        # first traced step, reference examples/tensorflow2/
        # tensorflow2_mnist.py:75-77): use the graph-capable broadcast
        # op, which bridges through tf.py_function at step time
        from . import broadcast as _broadcast_op

        for i, v in enumerate(variables):
            name = f"bcast.tf.{i}.{getattr(v, 'name', '') or 'var'}"
            val = _broadcast_op(tf.convert_to_tensor(v), root_rank,
                                name=name, process_set=process_set)
            v.assign(tf.cast(val, v.dtype))
        return
    handles = []
    for i, v in enumerate(variables):
        # index-prefixed: Keras 3 variable names are not unique ("bias"
        # repeats across layers) and in-flight names must be
        name = f"bcast.tf.{i}.{getattr(v, 'name', '') or 'var'}"
        h = _core.broadcast_async(v.numpy(), root_rank, name,
                                  process_set=process_set)
        handles.append((v, h))
    for v, h in handles:
        cur = np.asarray(v)  # works for tf.Variable and Keras 3 variables
        v.assign(np.asarray(_core.synchronize(h)).astype(
            cur.dtype).reshape(cur.shape))


def broadcast_object(obj, root_rank: int = 0, session=None, name=None,
                     process_set=None):
    return _core.broadcast_object(obj, root_rank=root_rank,
                                  process_set=process_set)


def broadcast_object_fn(root_rank: int = 0, session=None, name=None,
                        process_set=None):
    """Reference functions.py broadcast_object_fn: a callable for repeated
    broadcasts (TF1 session compatibility shape)."""

    def fn(obj):
        return broadcast_object(obj, root_rank=root_rank, name=name,
                                process_set=process_set)

    return fn


def allgather_object(obj, session=None, name=None, process_set=None):
    return _core.allgather_object(obj, process_set=process_set)


def broadcast_global_variables(root_rank: int = 0):
    """Reference functions.py broadcast_global_variables — gated. The TF1
    global-variables collection only exists in graph-session mode, whose
    data plane this runtime does not implement (variables there have no
    eager values to ship); TF2 eager has no global collection at all.
    Either way the supported idiom is explicit variables."""
    raise RuntimeError(
        "TF1 graph-mode global-variable broadcast is not supported on "
        "this runtime; use hvd.broadcast_variables(model.variables, "
        "root_rank) after building the model")
