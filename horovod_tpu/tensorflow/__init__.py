"""horovod_tpu.tensorflow — the TensorFlow-facing API (reference
horovod.tensorflow).

Mirrors /root/reference/horovod/tensorflow/__init__.py: ``allreduce`` with
the IndexedSlices→allgather sparse path (:54-154), ``grouped_allreduce``
(:156), ``DistributedOptimizer`` (:599), ``DistributedGradientTape``
(:743), plus mpi_ops surface (allgather/broadcast/alltoall, :follows
mpi_ops.py) and functions.py (broadcast_variables :47, object
collectives) — implemented over the horovod_tpu eager runtime: TF tensors
cross the boundary as host numpy; the collective itself executes on the
XLA/TPU data plane through the same negotiation/fusion cycle loop as every
other framework shim.

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    tape = hvd.DistributedGradientTape(tape)
    grads = tape.gradient(loss, model.trainable_variables)
    ...
    hvd.broadcast_variables(model.variables, root_rank=0)
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

import horovod_tpu as _core
import horovod_tpu.elastic as _elastic  # noqa: F401
from horovod_tpu import (  # noqa: F401  (topology + lifecycle re-exports)
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ProcessSet,
    ReduceOp,
    Sum,
    add_process_set,
    cross_rank,
    cross_size,
    tpu_enabled,
    tpu_built,
    rocm_built,
    mpi_threads_supported,
    gloo_enabled,
    gloo_built,
    ddl_built,
    cuda_built,
    ccl_built,
    global_process_set,
    init,
    is_homogeneous,
    is_initialized,
    mpi_built,
    mpi_enabled,
    nccl_built,
    remove_process_set,
    shutdown,
    start_timeline,
    stop_timeline,
)
from horovod_tpu.common.exceptions import HorovodInternalError  # noqa: F401


# worker-level (process) topology — reference shim semantics,
# defined once in common/worker.py
from horovod_tpu.common.worker import (  # noqa: F401
    local_rank,
    local_size,
    rank,
    size,
)

from .compression import Compression  # noqa: F401
from .functions import (  # noqa: F401
    allgather_object,
    broadcast_global_variables,
    broadcast_object,
    broadcast_object_fn,
    broadcast_variables,
)
from .sync_batch_norm import SyncBatchNormalization  # noqa: F401


def _to_np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        arr = t
    elif hasattr(t, "numpy"):
        arr = t.numpy()
    else:
        arr = np.asarray(t)
    if arr.dtype in (np.float64, np.int64):
        from ..common.util import warn_64bit_narrowing
        warn_64bit_narrowing(arr.dtype)
    return arr


def _from_np(result, dtype: tf.DType) -> tf.Tensor:
    # the wire may narrow 64-bit types (JAX runs with x64 disabled — TPUs
    # have no f64 ALUs); restore the caller's dtype, like the torch shim
    return tf.constant(np.asarray(result), dtype=dtype)


def _scale_factors(op, gradient_predivide_factor: float, nranks: int):
    """Reference DistributedOptimizer semantics: gradient_predivide_factor
    splits the averaging between pre- and post-division when op=Average."""
    if gradient_predivide_factor == 1.0:
        return op, 1.0, 1.0
    if op != Average:
        raise ValueError(
            "gradient_predivide_factor requires op=Average (reference "
            "tensorflow/__init__.py:624 check)")
    return (ReduceOp.SUM, 1.0 / gradient_predivide_factor,
            gradient_predivide_factor / nranks)


# ---------------------------------------------------------------------------
# collectives (reference tensorflow/__init__.py:54-200 + mpi_ops.py)
# ---------------------------------------------------------------------------

def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Reference tensorflow/__init__.py:54-154 — including the sparse path:
    an ``tf.IndexedSlices`` becomes an allgather of values and indices
    (every worker applies all updates; AVERAGE divides values by size)."""
    if isinstance(tensor, tf.IndexedSlices):
        avg = average if average is not None else (
            op in (None, Average, ReduceOp.AVERAGE))
        values = allgather(tensor.values, name=f"{name or 'sparse'}.values",
                           process_set=process_set)
        indices = allgather(tensor.indices, name=f"{name or 'sparse'}.indices",
                            process_set=process_set)
        if avg:
            n = (process_set or global_process_set()).cross_size
            values = values / tf.cast(n, values.dtype)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    # quant markers select the runtime's blockwise-quantized wire;
    # compress() below is identity for them (ops/compression.py)
    _qm = (compression if getattr(compression, "quant_spec", None)
           is not None else None)

    @tf.custom_gradient
    def _op(t_in):
        t, ctx = compression.compress(t_in)

        def _bridge(x):
            h = _core.allreduce_async(_to_np(x), average, name, op=op,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      process_set=process_set,
                                      compression=_qm)
            return _from_np(_core.synchronize(h), t.dtype)

        # Under tf.function the tensors are symbolic; the numpy bridge
        # must run at step time, not trace time. tf.py_function is the
        # graph-mode seam (the reference's AsyncOpKernels serve both
        # modes natively, mpi_ops.cc:383-431 — our XLA data plane keeps
        # one eager runtime and bridges the graph into it).
        if tf.executing_eagerly():
            out = _bridge(t)
        else:
            out = tf.py_function(_bridge, [t], t.dtype)
            out.set_shape(t.shape)
        out = compression.decompress(out, ctx)

        def grad(dy):
            # gradient of an allreduce is an allreduce of the gradient with
            # the same op (reference mpi_ops.py:124-171 gradient
            # registrations: sum→sum, average→average)
            return allreduce(dy, average=average, op=op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             name=f"{name}.grad" if name else None,
                             process_set=process_set)

        return out, grad

    return _op(tensor)


import itertools as _itertools

_group_counter = _itertools.count()


def grouped_allreduce(tensors, average=None, device_dense="",
                      device_sparse="", compression=Compression.none,
                      op=None, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None):
    """Reference tensorflow/__init__.py:156 — one logical fused op; the
    cycle loop flattens the group into a single collective."""
    # stable names (pass ``name``) keep the steady-state negotiation fast
    # path hot; unnamed calls get a unique base so concurrent groups can't
    # collide on the in-flight name guard
    base = name or f"grouped.tf.noname.{next(_group_counter)}"
    _qm = (compression if getattr(compression, "quant_spec", None)
           is not None else None)

    @tf.custom_gradient
    def _op(*ts):
        comp = [compression.compress(t) for t in ts]
        dtypes = [t.dtype for t, _ in comp]

        def _bridge(*xs):
            hs = [_core.allreduce_async(_to_np(x), average, f"{base}.{i}",
                                        op=op,
                                        prescale_factor=prescale_factor,
                                        postscale_factor=postscale_factor,
                                        process_set=process_set,
                                        compression=_qm)
                  for i, x in enumerate(xs)]
            return [_from_np(_core.synchronize(h), d)
                    for h, d in zip(hs, dtypes)]

        if tf.executing_eagerly():
            raw = _bridge(*[t for t, _ in comp])
        else:
            raw = tf.py_function(_bridge, [t for t, _ in comp], dtypes)
            for o, (t, _) in zip(raw, comp):
                o.set_shape(t.shape)
        outs = [compression.decompress(o, c)
                for o, (_, c) in zip(raw, comp)]

        def grad(*dys):
            # gradient of a grouped allreduce is a grouped allreduce of
            # the cotangents with the same op (reference grouped grad
            # registration)
            return grouped_allreduce(
                list(dys), average=average, compression=compression,
                op=op, prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                name=f"{base}.grad", process_set=process_set)

        return tuple(outs), grad

    return list(_op(*tensors))


def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Differentiable allgather (reference mpi_ops.py:212 gradient
    registration: allreduce-average the cotangent, then take this
    worker's slice)."""

    @tf.custom_gradient
    def _op(t_in):
        graph_mode = not tf.executing_eagerly()
        rows_cell: list[int] = []  # runtime row count, set by the forward
        start_cache: list[int] = []  # memoized (persistent tapes)

        def _bridge(x):
            arr = _to_np(x)
            rows_cell[:] = [int(arr.shape[0]) if arr.ndim else 0]
            h = _core.allgather_async(arr, name, process_set=process_set)
            return _from_np(_core.synchronize(h), tf.as_dtype(t_in.dtype))

        if graph_mode:
            out = tf.py_function(_bridge, [t_in], tf.as_dtype(t_in.dtype))
            out.set_shape(tf.TensorShape([None]).concatenate(
                t_in.shape[1:]))
        else:
            out = _bridge(t_in)

        def grad(dy):
            red = allreduce(dy, average=True, process_set=process_set,
                            name=f"{name}.grad" if name else None)

            def _slice(r):
                # workers contributed rows in rank order; ragged inputs
                # need everyone's row counts (one exchange, backward-only).
                # Eager mode memoizes it: the closure is fresh per forward
                # call, so the memo only ever serves repeated backward of
                # the same forward (persistent tapes — row counts fixed).
                # Graph mode must NOT memoize: the closure persists across
                # step executions, rows can differ per step (final partial
                # batch), and a rank skipping the exchange while another
                # runs it would deadlock the collective.
                local_rows = rows_cell[0]
                ps = process_set or global_process_set()
                if graph_mode or not start_cache:
                    if ps.cross_size <= 1:
                        start_cache[:] = [0]
                    else:
                        sizes = _core.synchronize(_core.allgather_async(
                            np.asarray([local_rows]),
                            f"{name or 'allgather'}.grad.sizes",
                            process_set=process_set))
                        start_cache[:] = [
                            int(np.sum(np.asarray(sizes)[:ps.cross_rank]))]
                start = start_cache[0]
                return r[start:start + local_rows]

            if graph_mode:
                back = tf.py_function(_slice, [red], red.dtype)
                back.set_shape(t_in.shape)
                return back
            return _slice(red)

        return out, grad

    return _op(tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Differentiable broadcast (reference mpi_ops.py:257 gradient:
    allreduce-average the cotangent; non-root workers get zeros)."""

    @tf.custom_gradient
    def _op(t_in):
        def _bridge(x):
            h = _core.broadcast_async(_to_np(x), root_rank, name,
                                      process_set=process_set)
            return _from_np(_core.synchronize(h), tf.as_dtype(t_in.dtype))

        if tf.executing_eagerly():
            out = _bridge(t_in)
        else:
            out = tf.py_function(_bridge, [t_in], tf.as_dtype(t_in.dtype))
            out.set_shape(t_in.shape)

        def grad(dy):
            red = allreduce(dy, average=True, process_set=process_set,
                            name=f"{name}.grad" if name else None)
            # root_rank is a *chip* index in the process set (core
            # broadcast semantics, ops/collectives.py); the gradient
            # belongs to the process that owns that chip
            import jax

            ps = process_set or global_process_set()
            is_root = (ps.devices[root_rank].process_index
                       == jax.process_index())
            return red if is_root else red * 0

        return out, grad

    return _op(tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Differentiable alltoall (reference mpi_ops.py:314 gradient: the
    cotangent routes back with splits = received_splits)."""
    @tf.custom_gradient
    def _op(t_in):
        def _bridge(x, s=None):
            h = _core.alltoall_async(
                _to_np(x), None if s is None else _to_np(s),
                name, process_set=process_set)
            out, recv = _core.synchronize(h)
            recv = np.asarray(recv)
            return (_from_np(out, tf.as_dtype(t_in.dtype)),
                    tf.constant(recv, dtype=tf.int32))

        if tf.executing_eagerly():
            out_t, recv_t = _bridge(t_in, splits)
        else:
            # splits may itself be a graph tensor (the backward path
            # feeds the forward's received_splits) — it must enter the
            # py_function as an input, not a closure capture
            inp = [t_in] if splits is None else [t_in, splits]
            out_t, recv_t = tf.py_function(
                _bridge, inp, [tf.as_dtype(t_in.dtype), tf.int32])
            out_t.set_shape(tf.TensorShape([None]).concatenate(
                t_in.shape[1:]))
            recv_t.set_shape(tf.TensorShape([None]))

        def grad(dy, _drecv=None):
            # the cotangent routes back with splits = received_splits;
            # recv_t is the forward's runtime output, a valid input to
            # the backward graph in both modes
            back, _ = alltoall(dy, splits=recv_t,
                               name=f"{name}.grad" if name else None,
                               process_set=process_set)
            return back

        return (out_t, recv_t), grad

    return _op(tensor)


def reducescatter(tensor, op=None, name: Optional[str] = None,
                  process_set: Optional[ProcessSet] = None):
    def _bridge(x):
        h = _core.reducescatter_async(_to_np(x), name, op=op,
                                      process_set=process_set)
        return _from_np(_core.synchronize(h), tf.as_dtype(tensor.dtype))

    if tf.executing_eagerly():
        return _bridge(tensor)
    out = tf.py_function(_bridge, [tensor], tf.as_dtype(tensor.dtype))
    out.set_shape(tf.TensorShape([None]).concatenate(tensor.shape[1:]))
    return out


def join() -> int:
    return _core.join()


def barrier(process_set: Optional[ProcessSet] = None):
    _core.barrier(process_set)


# graph-time scalar ops for elastic re-reads (reference mpi_ops.py:338-399
# size_op/rank_op: values that must be re-evaluated after hvd re-init
# instead of being baked into the graph as constants)

def size_op(process_set: Optional[ProcessSet] = None, name=None):
    return tf.py_function(
        lambda: (process_set or global_process_set()).cross_size, [],
        tf.int32)


def rank_op(name=None):
    return tf.py_function(lambda: rank(), [], tf.int32)


def local_size_op(name=None):
    return tf.py_function(lambda: local_size(), [], tf.int32)


def local_rank_op(name=None):
    return tf.py_function(lambda: local_rank(), [], tf.int32)


# ---------------------------------------------------------------------------
# DistributedGradientTape (reference tensorflow/__init__.py:743)
# ---------------------------------------------------------------------------

class _DistributedGradientTape(tf.GradientTape):
    """Wraps a live ``tf.GradientTape``: ``gradient()`` computes the local
    gradients, then allreduces them as one fused group. XLA overlapping and
    fusion replace the reference's _make_allreduce_grads_fn graph op."""

    def __init__(self, tape, device_dense, device_sparse, compression,
                 persistent, op, gradient_predivide_factor, sparse_as_dense,
                 process_set):
        self._tape = tape
        self._compression = compression
        self._op = op
        self._predivide = gradient_predivide_factor
        self._sparse_as_dense = sparse_as_dense
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return self._allreduce_grads(grads)

    def _allreduce_grads(self, grads):
        nranks = (self._process_set or global_process_set()).cross_size
        op, pre, post = _scale_factors(self._op, self._predivide, nranks)
        out, dense_idx, dense_grads = [None] * len(grads), [], []
        for i, g in enumerate(grads):
            if g is None:
                continue
            if isinstance(g, tf.IndexedSlices):
                if self._sparse_as_dense:
                    g = tf.convert_to_tensor(g)
                else:
                    out[i] = allreduce(g, op=self._op,
                                       process_set=self._process_set)
                    continue
            dense_idx.append(i)
            dense_grads.append(g)
        reduced = grouped_allreduce(dense_grads, op=op,
                                    compression=self._compression,
                                    prescale_factor=pre,
                                    postscale_factor=post,
                                    name="tape.grads",  # stable: steady-
                                    # state rounds hit the fast path
                                    process_set=self._process_set)
        for i, r in zip(dense_idx, reduced):
            out[i] = r
        return out


def DistributedGradientTape(gradtape, device_dense="", device_sparse="",
                            compression=Compression.none, persistent=False,
                            op=Average, gradient_predivide_factor=1.0,
                            sparse_as_dense=False,
                            process_set: Optional[ProcessSet] = None):
    """Reference tensorflow/__init__.py:743 — wrap a tf.GradientTape so
    ``gradient()`` returns globally-averaged gradients."""
    return _DistributedGradientTape(
        gradtape, device_dense, device_sparse, compression, persistent, op,
        gradient_predivide_factor, sparse_as_dense, process_set)


# ---------------------------------------------------------------------------
# DistributedOptimizer (reference tensorflow/__init__.py:599)
# ---------------------------------------------------------------------------

_warned_sharded_env = False


def _check_sharded_update(sharded_update):
    """Support-matrix gate for the ZeRO-1 mode (see DistributedOptimizer's
    docstring): explicit True is a hard error, the env knob only warns."""
    global _warned_sharded_env
    if sharded_update:
        raise ValueError(
            "sharded_update (ZeRO-1) is not supported for TF/keras "
            "optimizers; use horovod_tpu.DistributedGradientTransformation"
            "(..., sharded_update=True) for JAX/optax or "
            "horovod_tpu.torch.DistributedOptimizer(..., "
            "sharded_update=True) for torch (docs/sharded_optimizer.md)")
    if sharded_update is None and not _warned_sharded_env:
        from horovod_tpu.opt.sharded import sharded_update_enabled

        if sharded_update_enabled():
            import logging

            _warned_sharded_env = True
            logging.getLogger("horovod_tpu").warning(
                "HOROVOD_SHARDED_UPDATE is set but the TF/keras "
                "DistributedOptimizer does not implement the sharded "
                "update path; continuing with the replicated update "
                "(see docs/sharded_optimizer.md for supported frameworks)")


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False,
                         backward_passes_per_step=1, op=Average,
                         gradient_predivide_factor=1.0,
                         average_aggregated_gradients=False,
                         process_set: Optional[ProcessSet] = None,
                         sharded_update: Optional[bool] = None):
    """Wrap a TF optimizer so gradients are allreduced before being
    applied. Keras (2/3) optimizers go through the shared keras wrapper
    (reference defers the same way, tensorflow/__init__.py:679-698); legacy
    ``tf.compat.v1.train.Optimizer`` gets its ``compute_gradients``
    intercepted.

    ``sharded_update`` (ZeRO-1) is not implemented for the TF/keras
    wrappers — the apply path runs inside ``tf.function`` graphs this
    shim does not own, so there is no seam to split the step across
    ranks. Passing ``sharded_update=True`` raises; the
    ``HOROVOD_SHARDED_UPDATE`` env knob is ignored here (one warning)
    so a job-wide knob doesn't break keras entry points. Use the JAX
    ``hvd.DistributedGradientTransformation(..., sharded_update=True)``
    or the torch ``hvd.torch.DistributedOptimizer(...,
    sharded_update=True)`` paths instead (docs/sharded_optimizer.md)."""
    _check_sharded_update(sharded_update)
    import keras

    if isinstance(optimizer, keras.optimizers.Optimizer):
        from horovod_tpu._keras import create_distributed_optimizer

        return create_distributed_optimizer(
            optimizer, name=name, compression=compression, op=op,
            gradient_predivide_factor=gradient_predivide_factor,
            process_set=process_set,
            backward_passes_per_step=backward_passes_per_step,
            average_aggregated_gradients=average_aggregated_gradients)
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        return _LegacyDistributedOptimizer(
            optimizer, compression, op, gradient_predivide_factor,
            sparse_as_dense, process_set, name, use_locking,
            backward_passes_per_step=backward_passes_per_step,
            average_aggregated_gradients=average_aggregated_gradients)
    raise ValueError(
        "unsupported optimizer type for DistributedOptimizer: "
        f"{type(optimizer)}")


def DistributedAdasumOptimizer(optimizer, name=None,
                               compression=Compression.none,
                               backward_passes_per_step: int = 1):
    """Delta-Adasum optimizer (reference tensorflow/__init__.py:502
    _DistributedAdasumOptimizer): each worker applies its local updates;
    every ``backward_passes_per_step``-th step the accumulated model
    *delta* (var − start) is combined across workers with the
    scale-invariant Adasum reduction and committed (start += global_delta;
    var = start). TF2-eager re-design of the reference's tf.cond/slot graph
    machinery."""
    return _DistributedAdasumOptimizer(optimizer, name, compression,
                                       backward_passes_per_step)


class _DistributedAdasumOptimizer:
    def __init__(self, optimizer, name, compression,
                 backward_passes_per_step):
        self._opt = optimizer
        self._name = name or f"DistributedDelta{type(optimizer).__name__}"
        self._compression = compression
        self._bpps = int(backward_passes_per_step)
        # graph-safe state: a tf.Variable step counter and per-variable
        # "delta_start" snapshot variables keyed by v.ref() (the reference
        # keeps these as optimizer slots + a step_count variable — :520).
        # tf.Variable state survives tf.function tracing, unlike Python
        # ints, so the commit branch stays live inside model.fit's
        # compiled train_step; v.ref() is identity-stable (id() could be
        # recycled after GC).
        self._step_var: Optional[tf.Variable] = None
        self._start: dict = {}

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def _adasum_reduce_np(self, delta: np.ndarray, i: int) -> np.ndarray:
        t, ctx = self._compression.compress(tf.convert_to_tensor(delta))
        h = _core.allreduce_async(_to_np(t), None,
                                  f"adasum.delta.{self._name}.{i}",
                                  op=Adasum)
        out = _from_np(_core.synchronize(h), t.dtype)
        return np.asarray(self._compression.decompress(out, ctx))

    def _pre_update(self, variables):
        if self._step_var is None:
            self._step_var = tf.Variable(0, dtype=tf.int64, trainable=False,
                                         name="adasum_step_count")
        for v in variables:
            if v.ref() not in self._start:
                self._start[v.ref()] = tf.Variable(
                    v, trainable=False, name="adasum_delta_start")

    def _post_update(self, variables):
        self._step_var.assign_add(1)

        def commit():
            for i, v in enumerate(variables):
                start = self._start[v.ref()]
                local_delta = v - start
                # the eager-runtime Adasum rides a py_function so the
                # same code works traced (model.fit) and eager
                global_delta = tf.py_function(
                    lambda d, i=i: self._adasum_reduce_np(d.numpy(), i),
                    [local_delta], local_delta.dtype)
                global_delta.set_shape(v.shape)
                new_start = start + tf.cast(global_delta, v.dtype)
                start.assign(new_start)
                v.assign(new_start)
            return tf.constant(True)

        tf.cond(tf.equal(self._step_var % self._bpps, 0),
                commit, lambda: tf.constant(False))

    def apply_gradients(self, grads_and_vars, **kwargs):
        gvs = list(grads_and_vars)
        variables = [v for _, v in gvs]
        self._pre_update(variables)
        result = self._opt.apply_gradients(gvs, **kwargs)
        self._post_update(variables)
        return result

    def apply(self, grads, trainable_variables=None, **kwargs):
        """Keras 3's primary entry point — must be intercepted too, or a
        caller reaching the base optimizer's apply() would update weights
        without ever running the Adasum commit."""
        if trainable_variables is None:
            trainable_variables = getattr(self._opt,
                                          "_trainable_variables", None)
            if not trainable_variables:
                raise ValueError(
                    "DistributedAdasumOptimizer.apply needs "
                    "trainable_variables until the base optimizer is built")
        variables = list(trainable_variables)
        self._pre_update(variables)
        result = self._opt.apply(grads, variables, **kwargs)
        self._post_update(variables)
        return result

    def variables(self, *args, **kwargs):
        return self._opt.variables(*args, **kwargs)


class _LegacyDistributedOptimizer(tf.compat.v1.train.Optimizer):
    """tf.compat.v1 path (reference tensorflow/__init__.py:599-663):
    compute_gradients → allreduce → apply. With
    ``backward_passes_per_step > 1``, gradients accumulate locally and
    the allreduce + apply happen once per window
    (reference gradient_aggregation.py:16 LocalGradientAggregationHelper;
    eager redesign in tensorflow/gradient_aggregation.py)."""

    def __init__(self, opt, compression, op, gradient_predivide_factor,
                 sparse_as_dense, process_set, name, use_locking,
                 backward_passes_per_step: int = 1,
                 average_aggregated_gradients: bool = False):
        super().__init__(name=name or f"Distributed{type(opt).__name__}",
                         use_locking=use_locking)
        self._opt = opt
        self._tape_cfg = (compression, op, gradient_predivide_factor,
                          sparse_as_dense, process_set)
        self._agg_helper = None
        if backward_passes_per_step != 1:
            from .gradient_aggregation import LocalGradientAggregationHelper

            self._agg_helper = LocalGradientAggregationHelper(
                backward_passes_per_step,
                allreduce_func=self._allreduce_grads,
                sparse_as_dense=sparse_as_dense,
                average_aggregated_gradients=average_aggregated_gradients)

    def _allreduce_grads(self, grads):
        compression, op, predivide, sparse_as_dense, ps = self._tape_cfg
        helper = _DistributedGradientTape(
            None, "", "", compression, False, op, predivide,
            sparse_as_dense, ps)
        return helper._allreduce_grads(grads)

    def compute_gradients(self, *args, **kwargs):
        gvs = self._opt.compute_gradients(*args, **kwargs)
        if self._agg_helper is not None:
            grads = self._agg_helper.compute_gradients([g for g, _ in gvs])
        else:
            grads = self._allreduce_grads([g for g, _ in gvs])
        return [(g, v) for g, (_, v) in zip(grads, gvs)]

    def apply_gradients(self, *args, **kwargs):
        if self._agg_helper is not None:
            gs = kwargs.get("global_step")
            if gs is None and len(args) > 1:  # positional global_step
                gs = args[1]
            return self._agg_helper.apply_gradients(
                lambda: self._opt.apply_gradients(*args, **kwargs),
                global_step=gs)
        return self._opt.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._opt.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._opt.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._opt.variables(*args, **kwargs)


# hvd.elastic under the tensorflow namespace carries the TF state
# classes next to run (reference horovod/tensorflow/elastic.py exposes
# TensorFlowState/TensorFlowKerasState; verbatim scripts call
# `hvd.elastic.TensorFlowKerasState(model, opt, batch=0)`)
from horovod_tpu.common.util import module_namespace as _module_ns  # noqa: E402

from .elastic import TensorFlowKerasState, TensorFlowState  # noqa: E402,F401

elastic = _module_ns(_elastic, TensorFlowState=TensorFlowState,
                     TensorFlowKerasState=TensorFlowKerasState)
