"""Native runtime core loader (reference N25 build system role, slimmed:
one C++ shared library, built on demand with g++, consumed via ctypes —
pybind11 is deliberately not required).

``lib()`` returns the loaded library or None; callers keep a NumPy
fallback so the framework stays fully functional without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

LOG = logging.getLogger("horovod_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "core.cc")
_SO = os.path.join(_HERE, "libhvdcore.so")
_lock = threading.Lock()
# .so path -> loaded CDLL (or None after a failed attempt). Keyed by path
# because sanitized builds live under their own filenames — a TSan .so
# must never be mtime-fresh enough to serve a later normal-mode run.
_libs: dict = {}

_SANITIZERS = ("address", "thread")


def _sanitize_mode() -> str:
    """Validated HOROVOD_NATIVE_SANITIZE value ("" when unset/invalid)."""
    from ..common import env as env_schema

    v = os.environ.get(env_schema.HOROVOD_NATIVE_SANITIZE, "").strip().lower()
    if v and v not in _SANITIZERS:
        LOG.warning("ignoring HOROVOD_NATIVE_SANITIZE=%r (expected one of %s)",
                    v, "|".join(_SANITIZERS))
        return ""
    return v


def _so_path(mode: str) -> str:
    if not mode:
        return _SO
    return os.path.join(_HERE, f"libhvdcore-{mode[0]}san.so")


def _build(so: str, mode: str) -> bool:
    # N launcher workers on one host all build on first use; the shared
    # atomic-replace helper keeps concurrent g++ runs from truncating
    # each other's output (0o777: .so keeps exec bits under the umask)
    from ..common.util import atomic_tmp

    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
    if mode:
        cmd += [f"-fsanitize={mode}", "-g", "-fno-omit-frame-pointer"]
    try:
        with atomic_tmp(so, mode=0o777) as tmp:
            subprocess.run(
                cmd + ["-o", tmp, _SRC, "-lpthread"],
                check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:
        LOG.debug("native core build failed (%s); using numpy fallback", e)
        return False


def lib():
    """Load (building if needed) the native core; None on any failure.

    ``HOROVOD_NATIVE_SANITIZE=address|thread`` builds/loads an
    instrumented variant instead (loading the ASan variant additionally
    requires libasan in LD_PRELOAD when the interpreter itself is not
    sanitized — see tests/test_native_sanitize.py)."""
    from ..common import env as env_schema

    mode = _sanitize_mode()
    so = _so_path(mode)
    if so in _libs:
        return _libs[so]
    with _lock:
        if so in _libs:
            return _libs[so]
        _libs[so] = None
        if os.environ.get(env_schema.HOROVOD_TPU_DISABLE_NATIVE,
                          "") in ("1", "true"):
            return None
        if not os.path.exists(so) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(so)):
            if not _build(so, mode):
                return None
        try:
            L = ctypes.CDLL(so)
            L.hvd_pack.restype = ctypes.c_int64
            L.hvd_pack.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.c_int, ctypes.c_void_p]
            L.hvd_unpack.restype = ctypes.c_int64
            L.hvd_unpack.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int]
            L.hvd_tl_create.restype = ctypes.c_void_p
            L.hvd_tl_create.argtypes = [ctypes.c_int64]
            L.hvd_tl_destroy.argtypes = [ctypes.c_void_p]
            L.hvd_tl_push.restype = ctypes.c_int
            L.hvd_tl_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64]
            L.hvd_tl_drain.restype = ctypes.c_int64
            L.hvd_tl_drain.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int64]
            L.hvd_tl_dropped.restype = ctypes.c_int64
            L.hvd_tl_dropped.argtypes = [ctypes.c_void_p]
            if L.hvd_abi_version() != 1:
                return None
            _libs[so] = L
        except Exception as e:
            LOG.debug("native core load failed: %s", e)
    return _libs[so]


def _pack_into(arrays, buf) -> None:
    """Batched memcpy of contiguous ``arrays`` into uint8 ``buf`` (native
    parallel memcpy when available, numpy loop otherwise)."""
    import numpy as np

    L = lib()
    if L is None or len(arrays) < 2:
        off = 0
        for a in arrays:
            buf[off:off + a.nbytes] = a.view(np.uint8).reshape(-1)
            off += a.nbytes
    else:
        n = len(arrays)
        srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
        sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
        L.hvd_pack(srcs, sizes, n, buf.ctypes.data)


_PENDING = object()  # slot leased, completion token not yet attached

_staging_handles = None


def _staging_metrics():
    """(acquire{ring}, acquire{alloc}, reuse, inflight gauge) — resolved
    lazily and failure-tolerant so _native never depends on the metrics
    registry being importable."""
    global _staging_handles
    if _staging_handles is None:
        try:
            from ..utils import metrics as metrics_mod

            reg = metrics_mod.get_registry()
            _staging_handles = (
                reg.counter("hvd_staging_acquire_total",
                            "staging buffer acquisitions", source="ring"),
                reg.counter("hvd_staging_acquire_total",
                            "staging buffer acquisitions", source="alloc"),
                reg.counter("hvd_staging_reuse_total",
                            "staging ring slots reused"),
                reg.gauge("hvd_staging_inflight",
                          "staging slots leased or awaiting transfer"),
            )
        except Exception:  # pragma: no cover - metrics always importable
            class _Null:
                def inc(self, n=1):
                    pass

                def set(self, v):
                    pass

            _staging_handles = (_Null(), _Null(), _Null(), _Null())
    return _staging_handles


class _StagingLease:
    """Handle for one leased ring slot. ``retire(token)`` returns the slot:
    with ``token=None`` the slot frees immediately; with a token exposing
    ``is_ready()`` (a jax.Array) the slot stays unavailable until the
    async consumer of the staged bytes has finished with them."""

    __slots__ = ("_ring", "_index", "_done")

    def __init__(self, ring, index):
        self._ring = ring
        self._index = index
        self._done = False

    def retire(self, token=None):
        if self._done:
            return
        self._done = True
        self._ring._retire(self._index, token)


class StagingRing:
    """Ring of persistent host staging buffers for the fusion pack path.

    The legacy ``FusionBuffer.pack`` allocated a fresh buffer per call
    because the eager collective consumes the staged bytes asynchronously
    (the device transfer — or, on the CPU backend, the zero-copy device
    array itself — may alias the host memory). The ring keeps that safety
    with in-flight tracking instead of allocation: a slot is handed out
    again only once its completion token reports ``is_ready()``, i.e. the
    compiled program that read the staged bytes has produced its outputs.
    Slots are allocated lazily at full capacity (grow-only), so an idle
    runtime with a 128 MiB threshold does not pin slots×128 MiB."""

    def __init__(self, nbytes: int, slots: int = 4):
        from ..utils import lockcheck

        self.capacity = max(0, int(nbytes))
        self.slots = max(1, int(slots))
        self._lock = lockcheck.make_lock("native.staging_ring")
        self._bufs = [None] * self.slots  # guarded-by: _lock
        self._tokens = [None] * self.slots  # guarded-by: _lock
        self._used = [False] * self.slots  # guarded-by: _lock

    def _inflight(self) -> int:
        n = 0
        # internal helper: every caller already holds _lock
        for t in self._tokens:  # hvdlint: disable=lock-discipline
            if t is _PENDING:
                n += 1
            elif t is not None and not self._token_done(t):
                n += 1
        return n

    @staticmethod
    def _token_done(token) -> bool:
        try:
            return bool(token.is_ready())
        except Exception:
            return True  # dead/unknown token: don't wedge the slot forever

    def acquire(self, total: int):
        """Lease a slot with >= ``total`` bytes. Returns ``(buf, lease)``
        where ``buf`` is a uint8 view of exactly ``total`` bytes, or
        ``(None, None)`` when no slot fits (oversize chunk or all slots
        busy) — callers fall back to a fresh allocation."""
        import numpy as np

        m = _staging_metrics()
        if total > self.capacity:
            m[1].inc()
            return None, None
        with self._lock:
            for i in range(self.slots):
                t = self._tokens[i]
                if t is _PENDING:
                    continue
                if t is not None and not self._token_done(t):
                    continue
                if self._bufs[i] is None:
                    self._bufs[i] = np.empty(self.capacity, dtype=np.uint8)
                self._tokens[i] = _PENDING
                m[0].inc()
                if self._used[i]:
                    m[2].inc()
                self._used[i] = True
                m[3].set(self._inflight())
                return self._bufs[i][:total], _StagingLease(self, i)
        m[1].inc()
        return None, None

    def _retire(self, index: int, token):
        with self._lock:
            self._tokens[index] = token
            _staging_metrics()[3].set(self._inflight())

    def allocated_bytes(self) -> int:
        """Host bytes currently pinned by lazily-allocated slots (each
        allocated slot holds ``capacity`` bytes regardless of lease
        state) — the memledger's staging_ring component attribution."""
        with self._lock:
            return sum(int(b.nbytes) for b in self._bufs if b is not None)

    def resize(self, nbytes: int):
        """Adopt a new capacity (fusion threshold changed). Existing
        buffers are dropped — in-flight consumers hold their own
        references, so the memory survives until they finish."""
        nbytes = max(0, int(nbytes))
        with self._lock:
            if nbytes == self.capacity:
                return
            self.capacity = nbytes
            self._bufs = [None] * self.slots
            self._tokens = [None] * self.slots
            self._used = [False] * self.slots

    def set_slots(self, slots: int):
        """Adopt a new slot count (autotuner ring-depth knob). Same
        drop-and-release contract as ``resize``: in-flight consumers hold
        their own buffer references, so shrinking never frees bytes a
        pending transfer still reads."""
        slots = max(1, int(slots))
        with self._lock:
            if slots == self.slots:
                return
            self.slots = slots
            self._bufs = [None] * slots
            self._tokens = [None] * slots
            self._used = [False] * slots


def chain_dispatch(buffer: "FusionBuffer", steps):
    """Megaplan steady-state execution: run a captured whole-step chunk
    schedule as ONE chained dispatch through the staging ring.

    ``steps`` is the prebuilt schedule — ``(plan, arrays, on_device)``
    per chunk, in captured order, where ``plan`` is a compiled
    ``collectives.FusedChunkPlan``. Host chunks stage through a leased
    ring slot (native parallel memcpy when the core is built — the
    mandatory numpy fallback rides ``_pack_into``) and the lease retires
    on the chunk's first output token, exactly the per-chunk contract of
    ``ops/queue.py``; device chunks launch their compiled program
    directly. No negotiation, no grouping, no plan lookup — the per-step
    Python the megaplan eliminates.

    Returns ``(outs, exc)``: ``outs`` holds the per-chunk output lists
    for every chunk that fully dispatched; ``exc`` is the failure that
    stopped the chain (None on success). A mid-chain failure retires the
    failing chunk's lease with ``None`` (the ring is never left torn)
    and stops — the caller fails the remaining entries and degrades to
    negotiated mode."""
    outs = []
    for plan, arrays, on_device in steps:
        try:
            if on_device:
                outs.append(plan.execute(arrays))
                continue
            flat, lease = buffer.pack_leased(arrays)
            try:
                parts = plan.execute(flat)
            except Exception:
                if lease is not None:
                    lease.retire(None)
                raise
            if lease is not None:
                lease.retire(parts[0])
            outs.append(parts)
        except Exception as exc:
            return outs, exc
    return outs, None


class FusionBuffer:
    """Fusion pack/unpack helper (reference fusion_buffer_manager.h:40 +
    the MemcpyIn/Out pair, collective_operations.h:65-88): batched,
    multi-threaded memcpy of N tensors into one flat buffer via the native
    core. ``pack_leased`` stages into a persistent ring slot (reused only
    after the in-flight consumer finishes — see StagingRing); ``pack``
    keeps the legacy fresh-allocation contract for callers that hold the
    buffer indefinitely."""

    def __init__(self, nbytes: int = 0, slots: int = None):
        if slots is None:
            slots = 4
            try:
                from ..common import env as env_mod

                slots = env_mod.get_int(
                    env_mod.HOROVOD_STAGING_RING_SLOTS, 4)
            except Exception:
                pass
        self.nbytes = nbytes
        self.ring = StagingRing(nbytes, slots)

    def resize(self, nbytes: int):
        self.nbytes = nbytes
        self.ring.resize(nbytes)

    def set_slots(self, slots: int):
        self.ring.set_slots(slots)

    def allocated_bytes(self) -> int:
        """Staging-ring host bytes actually allocated (memledger pull)."""
        return self.ring.allocated_bytes()

    def pack_leased(self, arrays):
        """Pack into a leased ring slot. Returns ``(flat, lease)`` where
        ``flat`` is the packed array viewed as the first array's dtype and
        ``lease`` is a ``_StagingLease`` to retire once the consumer's
        completion token exists — or ``None`` when the ring was bypassed
        (oversize/busy) and the buffer is freshly owned."""
        import numpy as np

        arrays = [np.ascontiguousarray(a) for a in arrays]
        total = sum(a.nbytes for a in arrays)
        buf, lease = self.ring.acquire(total)
        if buf is None:
            buf = np.empty(total, dtype=np.uint8)
        _pack_into(arrays, buf)
        return buf.view(arrays[0].dtype), lease

    def pack(self, arrays) -> "np.ndarray":
        """Pack contiguous arrays into one flat freshly-allocated array
        (dtype of the first array): the caller owns the result with no
        reuse hazard, at the cost of an allocation per call."""
        import numpy as np

        arrays = [np.ascontiguousarray(a) for a in arrays]
        total = sum(a.nbytes for a in arrays)
        buf = np.empty(total, dtype=np.uint8)
        _pack_into(arrays, buf)
        return buf.view(arrays[0].dtype)

    @staticmethod
    def unpack(flat, shapes, dtype):
        """Slice a reduced flat array back into per-tensor arrays."""
        import numpy as np

        flat = np.ascontiguousarray(np.asarray(flat))
        outs, sizes = [], []
        for s in shapes:
            sizes.append(int(np.prod(s, dtype=np.int64)))
        L = lib()
        if L is None:
            off = 0
            for s, n in zip(shapes, sizes):
                outs.append(flat[off:off + n].reshape(s))
                off += n
            return outs
        outs = [np.empty(s, dtype=flat.dtype) for s in shapes]
        n = len(outs)
        dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
        bts = (ctypes.c_int64 * n)(
            *[o.nbytes for o in outs])
        L.hvd_unpack(flat.ctypes.data, dsts, bts, n)
        return outs
