"""Native runtime core loader (reference N25 build system role, slimmed:
one C++ shared library, built on demand with g++, consumed via ctypes —
pybind11 is deliberately not required).

``lib()`` returns the loaded library or None; callers keep a NumPy
fallback so the framework stays fully functional without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

LOG = logging.getLogger("horovod_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "core.cc")
_SO = os.path.join(_HERE, "libhvdcore.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # N launcher workers on one host all build on first use; the shared
    # atomic-replace helper keeps concurrent g++ runs from truncating
    # each other's output (0o777: .so keeps exec bits under the umask)
    from ..common.util import atomic_tmp

    try:
        with atomic_tmp(_SO, mode=0o777) as tmp:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, _SRC, "-lpthread"],
                check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:
        LOG.debug("native core build failed (%s); using numpy fallback", e)
        return False


def lib():
    """Load (building if needed) the native core; None on any failure."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HOROVOD_TPU_DISABLE_NATIVE", "") in ("1", "true"):
            return None
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        try:
            L = ctypes.CDLL(_SO)
            L.hvd_pack.restype = ctypes.c_int64
            L.hvd_pack.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.c_int, ctypes.c_void_p]
            L.hvd_unpack.restype = ctypes.c_int64
            L.hvd_unpack.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int]
            L.hvd_tl_create.restype = ctypes.c_void_p
            L.hvd_tl_create.argtypes = [ctypes.c_int64]
            L.hvd_tl_destroy.argtypes = [ctypes.c_void_p]
            L.hvd_tl_push.restype = ctypes.c_int
            L.hvd_tl_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64]
            L.hvd_tl_drain.restype = ctypes.c_int64
            L.hvd_tl_drain.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int64]
            L.hvd_tl_dropped.restype = ctypes.c_int64
            L.hvd_tl_dropped.argtypes = [ctypes.c_void_p]
            if L.hvd_abi_version() != 1:
                return None
            _lib = L
        except Exception as e:
            LOG.debug("native core load failed: %s", e)
            _lib = None
    return _lib


class FusionBuffer:
    """Fusion pack/unpack helper (reference fusion_buffer_manager.h:40 +
    the MemcpyIn/Out pair, collective_operations.h:65-88): batched,
    multi-threaded memcpy of N tensors into one flat buffer via the native
    core. Each ``pack`` returns a *freshly allocated* buffer: the eager
    collective consumes its input asynchronously (and the device transfer
    may alias the host memory), so a reused scratch buffer could be
    overwritten before the in-flight collective reads it."""

    def __init__(self, nbytes: int = 0):
        self.nbytes = nbytes  # advisory initial size; kept for API parity

    def pack(self, arrays) -> "np.ndarray":
        """Pack contiguous arrays into one flat array (dtype of the first
        array) using the native parallel memcpy when available."""
        import numpy as np

        arrays = [np.ascontiguousarray(a) for a in arrays]
        total = sum(a.nbytes for a in arrays)
        buf = np.empty(total, dtype=np.uint8)
        L = lib()
        if L is None or len(arrays) < 2:
            off = 0
            for a in arrays:
                buf[off:off + a.nbytes] = a.view(np.uint8).reshape(-1)
                off += a.nbytes
        else:
            n = len(arrays)
            srcs = (ctypes.c_void_p * n)(
                *[a.ctypes.data for a in arrays])
            sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
            L.hvd_pack(srcs, sizes, n, buf.ctypes.data)
        return buf.view(arrays[0].dtype)

    @staticmethod
    def unpack(flat, shapes, dtype):
        """Slice a reduced flat array back into per-tensor arrays."""
        import numpy as np

        flat = np.ascontiguousarray(np.asarray(flat))
        outs, sizes = [], []
        for s in shapes:
            sizes.append(int(np.prod(s, dtype=np.int64)))
        L = lib()
        if L is None:
            off = 0
            for s, n in zip(shapes, sizes):
                outs.append(flat[off:off + n].reshape(s))
                off += n
            return outs
        outs = [np.empty(s, dtype=flat.dtype) for s in shapes]
        n = len(outs)
        dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
        bts = (ctypes.c_int64 * n)(
            *[o.nbytes for o in outs])
        L.hvd_unpack(flat.ctypes.data, dsts, bts, n)
        return outs
