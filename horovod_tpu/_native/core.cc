// horovod_tpu native runtime core.
//
// TPU-native equivalents of the reference's native hot paths (SURVEY.md
// N9 fusion buffer memcpy in/out — collective_operations.h:65-88 /
// fusion_buffer_manager.h; N11 timeline SPSC queue — timeline.h:84-100):
//
//  * hvd_pack / hvd_unpack: batched memcpy of N tensors into/out of one
//    persistent fusion buffer, multi-threaded above a size threshold
//    (the role of the reference's MemcpyInFusionBuffer + batched D2D
//    kernel, done host-side here because the device side is one fused
//    XLA program).
//  * an SPSC ring for timeline events so the hot enqueue path never
//    blocks on the writer thread (reference boost::lockfree::spsc_queue).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -o libhvdcore.so core.cc -lpthread

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Fusion buffer pack/unpack
// ---------------------------------------------------------------------------

// Parallel memcpy threshold: below this total size the thread spawn costs
// more than the copy.
static const int64_t kParallelBytes = 1 << 22;  // 4 MiB

static void copy_ranges(const void** srcs, void** dsts,
                        const int64_t* sizes, int begin, int end) {
  for (int i = begin; i < end; ++i) {
    std::memcpy(dsts[i], srcs[i], static_cast<size_t>(sizes[i]));
  }
}

// Pack n tensors (srcs[i], sizes[i] bytes) contiguously into dst.
// Returns total bytes packed.
int64_t hvd_pack(const void** srcs, const int64_t* sizes, int n, void* dst) {
  std::vector<void*> dsts(n);
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    dsts[i] = static_cast<char*>(dst) + off;
    off += sizes[i];
  }
  unsigned hw = std::thread::hardware_concurrency();
  if (off < kParallelBytes || n < 2 || hw < 2) {
    copy_ranges(srcs, dsts.data(), sizes, 0, n);
    return off;
  }
  int nthreads = static_cast<int>(hw < 8 ? hw : 8);
  if (nthreads > n) nthreads = n;
  std::vector<std::thread> workers;
  int per = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int b = t * per, e = b + per > n ? n : b + per;
    if (b >= e) break;
    workers.emplace_back(copy_ranges, srcs, dsts.data(), sizes, b, e);
  }
  for (auto& w : workers) w.join();
  return off;
}

// Unpack a contiguous src into n tensors (dsts[i], sizes[i] bytes).
int64_t hvd_unpack(const void* src, void** dsts, const int64_t* sizes,
                   int n) {
  std::vector<const void*> srcs(n);
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    srcs[i] = static_cast<const char*>(src) + off;
    off += sizes[i];
  }
  unsigned hw = std::thread::hardware_concurrency();
  if (off < kParallelBytes || n < 2 || hw < 2) {
    copy_ranges(srcs.data(), dsts, sizes, 0, n);
    return off;
  }
  int nthreads = static_cast<int>(hw < 8 ? hw : 8);
  if (nthreads > n) nthreads = n;
  std::vector<std::thread> workers;
  int per = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int b = t * per, e = b + per > n ? n : b + per;
    if (b >= e) break;
    workers.emplace_back(copy_ranges, srcs.data(), dsts, sizes, b, e);
  }
  for (auto& w : workers) w.join();
  return off;
}

// ---------------------------------------------------------------------------
// Timeline SPSC ring (single producer: enqueue path; single consumer:
// writer thread)
// ---------------------------------------------------------------------------

// Multi-producer (user threads + cycle thread both emit events), single
// consumer (writer thread). Producers serialize on a mutex — event rates
// are low and payloads tiny, so contention is negligible; the consumer
// drains lock-free against the atomic head.
struct TlRing {
  std::vector<std::string> slots;
  std::atomic<uint64_t> head{0};  // next write (producers)
  std::atomic<uint64_t> tail{0};  // next read (consumer)
  uint64_t capacity;
  std::atomic<uint64_t> dropped{0};
  std::mutex produce_mu;
};

void* hvd_tl_create(int64_t capacity) {
  TlRing* r = new TlRing();
  r->capacity = static_cast<uint64_t>(capacity);
  r->slots.resize(r->capacity);
  return r;
}

void hvd_tl_destroy(void* ring) { delete static_cast<TlRing*>(ring); }

// Returns 1 on success, 0 when full (event dropped — matches the
// reference's lossy-under-pressure queue semantics).
int hvd_tl_push(void* ring, const char* data, int64_t len) {
  TlRing* r = static_cast<TlRing*>(ring);
  std::lock_guard<std::mutex> lock(r->produce_mu);
  uint64_t head = r->head.load(std::memory_order_relaxed);
  uint64_t tail = r->tail.load(std::memory_order_acquire);
  if (head - tail >= r->capacity) {
    r->dropped.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  r->slots[head % r->capacity].assign(data, static_cast<size_t>(len));
  r->head.store(head + 1, std::memory_order_release);
  return 1;
}

// Drain up to buflen bytes of newline-separated events into buf.
// Returns bytes written (0 = empty).
int64_t hvd_tl_drain(void* ring, char* buf, int64_t buflen) {
  TlRing* r = static_cast<TlRing*>(ring);
  int64_t written = 0;
  uint64_t tail = r->tail.load(std::memory_order_relaxed);
  uint64_t head = r->head.load(std::memory_order_acquire);
  while (tail < head) {
    const std::string& s = r->slots[tail % r->capacity];
    int64_t need = static_cast<int64_t>(s.size()) + 1;
    if (written + need > buflen) break;
    std::memcpy(buf + written, s.data(), s.size());
    written += static_cast<int64_t>(s.size());
    buf[written++] = '\n';
    ++tail;
  }
  r->tail.store(tail, std::memory_order_release);
  return written;
}

int64_t hvd_tl_dropped(void* ring) {
  return static_cast<int64_t>(
      static_cast<TlRing*>(ring)->dropped.load(std::memory_order_relaxed));
}

int hvd_abi_version() { return 1; }

}  // extern "C"
