"""Fleet health engine: bounded time-series history + online drift
detection with suspect attribution.

Every telemetry surface this runtime grew so far — the metrics registry,
``GET /perf``//``/memory``//``/anatomy``//``/checkpoint``, the SLO
engine — answers "how does the job look *right now*"; the only
regression detector (tools/benchguard) runs offline against banked
``BENCH_r*.json`` rounds. Distributed-training regressions are temporal
(Horovod's own timeline work, arXiv:1802.05799; the MVAPICH
characterization, arXiv:1810.11112): a job healthy at step 1k silently
degrades by step 10k — straggler emergence, plan-cache decay, wire
inflation. This module closes that gap at runtime, in three layers:

- **History store**: a declared subset of the live signals (step time,
  negotiation latency, exposed-comm fraction, phase shares, plan /
  megaplan hit signals, wire bytes/step, straggler waits, checkpoint
  lag, memory peak) is sampled on the MetricsDumper cadence into
  fixed-size per-series rings (``HOROVOD_HEALTH_BUFFER`` points each)
  plus a mean-downsampled tier retaining ``DOWNSAMPLE_EVERY``× longer.
- **Online drift/anomaly detector**: per series, a robust baseline
  (median + MAD, frozen after ``HOROVOD_HEALTH_WARMUP`` samples) drives
  direction-aware robust-z verdicts — a sustained excursion latches a
  ``drift`` anomaly after ``DEBOUNCE_SAMPLES`` consecutive bad samples,
  an extreme single sample latches a ``spike`` immediately. Anomalies
  latch once per episode (the SLO-engine convention) and re-arm after
  ``CLEAR_SAMPLES`` consecutive in-bound samples. A latch increments
  ``hvd_health_anomaly_total{series,kind}``, notes a ``health``
  flight-recorder event, escalates through
  ``StallInspector.note_health_anomaly`` (naming series, observed vs
  baseline, and the suspect rank when straggler attribution is fresh),
  and — for the goodput series the autotuner optimizes — feeds the
  workload-shift re-tune path (``Autotuner.note_health_drift``).
- **Fleet merge + attribution**: per-rank snapshots ride the dump
  cadence under ``health/rank{k}``; the launcher's auth-exempt
  ``GET /history`` (windowed per-series query) and ``GET /health``
  (single fleet verdict: healthy/degraded/critical, suspects ranked by
  cross-rank outlier score via :func:`fleet_view`) merge them.

Zero-cost contract (same as utils/perfledger.py and utils/anatomy.py,
enforced by hvdlint's zero-cost-hooks rule and
benchmarks/health_overhead.py): with ``HOROVOD_HEALTH`` unset no engine
exists, the only hook (the MetricsDumper flush) pays one ``is None``
check, and no ``hvd_health_*`` series is registered. Metric handles are
resolved in ``HealthEngine.__init__`` — lazily at enable — so the off
state adds zero series.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

from ..common import env as env_schema
from . import flightrec as flightrec_mod
from . import lockcheck

LOG = logging.getLogger("horovod_tpu")

#: KV scope the MetricsDumper pushes per-rank history snapshots under
#: (``health/rank{k}``); the launcher's ``GET /history`` and
#: ``GET /health`` merge the scope.
KV_SCOPE = "health"

DEFAULT_CAPACITY = 512
DEFAULT_WARMUP = 20

#: Every this-many raw samples collapse into one mean point in the
#: long-retention tier, so a full ring covers ``capacity`` dumps at full
#: resolution plus ``capacity * DOWNSAMPLE_EVERY`` dumps downsampled.
DOWNSAMPLE_EVERY = 8

#: Robust-z a sample must cross *in the series' bad direction* before it
#: counts toward a drift latch; twice that latches a spike immediately.
Z_DRIFT = 6.0
Z_SPIKE = 12.0
#: Consecutive over-threshold samples before a drift latches (one noisy
#: dump window must not page anyone) and consecutive in-bound samples
#: before a latched anomaly clears and the series re-arms.
DEBOUNCE_SAMPLES = 2
CLEAR_SAMPLES = 2
#: This many simultaneously latched series escalate the local verdict
#: from degraded to critical.
CRITICAL_ANOMALIES = 3

#: Newest raw/downsampled points carried per series in each KV push —
#: bounds the push payload; the full rings stay local (``history()`` /
#: the ``HOROVOD_HEALTH_FILE`` on-exit dump).
PUSH_WINDOW = 120

#: Active-anomaly weight in the cross-rank suspect score: a rank whose
#: own detector latched outranks one that merely reads high this window.
ANOMALY_SUSPECT_WEIGHT = 10.0

#: Score weight each anomalous rank's coordinator straggler attribution
#: adds to the rank it names. A lockstep control plane slows EVERY rank
#: when one drags (victims wait at the barrier), so per-rank magnitudes
#: alone cannot separate culprit from victims — a victim stuck at the
#: barrier often reads HIGHER z than the culprit. The coordinator's
#: last-to-submit verdict is mechanical truth about who held the round,
#: so beyond this score weight the naming COUNT is the primary suspect
#: sort key; the outlier score only orders ranks with equal namings.
STRAGGLER_SUSPECT_WEIGHT = 40.0

#: The declared series: (name, bad direction, what it samples). "high"
#: means drifting up is the regression; "low" means drifting down is.
#: Sources are the perf ledger's per-window records plus non-creating
#: registry reads of feature-gated gauges/histograms — a source whose
#: owning feature is off contributes no samples (and no series ring).
SERIES = (
    ("step_time_ms", "high", "mean step wall time over the dump window"),
    ("negotiate_ms", "high",
     "mean negotiation-round time (stall slice included) over the window"),
    ("exposed_comm_frac", "high",
     "fraction of window wall time exposed to communication"),
    ("stall_share", "high",
     "fraction of window wall time spent waiting on attributed stragglers"),
    ("plan_hit_rate", "low", "fused-plan cache hit rate over the window"),
    ("megaplan_active", "low",
     "1 while a captured whole-step megaplan is replaying, 0 when armed"),
    ("wire_bytes_per_step", "high",
     "mean data-plane wire bytes per step over the window"),
    ("straggler_wait_ms", "high",
     "p95 coordinator-attributed straggler wait (cumulative histogram)"),
    ("ckpt_lag_steps", "high",
     "recorded steps ahead of the newest durably committed checkpoint"),
    ("mem_peak_bytes", "high", "device-memory peak bytes (memledger)"),
)

DIRECTIONS = {name: direction for name, direction, _ in SERIES}

#: A latched drift on one of these feeds the autotuner's workload-shift
#: re-tune path: they are exactly what its goodput objective optimizes.
AUTOTUNE_SERIES = ("step_time_ms", "exposed_comm_frac")

_VERDICT_LEVELS = {"healthy": 0, "degraded": 1, "critical": 2}


class SeriesRing:
    """One series' bounded history: a raw ring plus the mean-downsampled
    long-retention tier. Not self-locking — the engine's lock guards it.
    """

    __slots__ = ("raw", "tier", "total", "_pending")

    def __init__(self, capacity: int):
        self.raw = collections.deque(maxlen=capacity)
        self.tier = collections.deque(maxlen=capacity)
        self.total = 0
        self._pending: List[Tuple[float, float]] = []

    def append(self, ts: float, value: float) -> None:
        self.raw.append((ts, value))
        self.total += 1
        self._pending.append((ts, value))
        if len(self._pending) >= DOWNSAMPLE_EVERY:
            first_ts = self._pending[0][0]
            mean = sum(v for _, v in self._pending) / len(self._pending)
            self.tier.append((first_ts, mean))
            self._pending = []


def _baselines(detectors: Dict[str, "_Detector"]) -> dict:
    """Frozen per-series baselines view (call with the engine lock held
    when passing a live detector table)."""
    return {name: {"median": round(d.median, 6),
                   "scale": round(d.scale, 6), "warmup": d.warmup}
            for name, d in sorted(detectors.items())
            if d.median is not None}


def _lower_median(values: List[float]) -> float:
    s = sorted(values)
    return s[(len(s) - 1) // 2]


def _robust_scale(values: List[float], median: float) -> float:
    """MAD-derived scale with a floor: a warmup window of near-identical
    samples (CI smoke, idle job) must not turn every later jitter into a
    million-sigma anomaly. The floor is 5% of the baseline magnitude."""
    mad = _lower_median([abs(v - median) for v in values])
    return max(1.4826 * mad, 0.05 * abs(median), 1e-9)


class _Detector:
    """Per-series online drift detector (engine-lock guarded).

    Learns a frozen median/MAD baseline from the first ``warmup``
    samples, then judges each sample by direction-aware robust z-score
    with debounce, latch-once, and re-arm — the SLO engine's breach
    semantics applied to a learned bound instead of a declared one.
    """

    __slots__ = ("name", "direction", "warmup", "window", "median",
                 "scale", "bad_streak", "ok_streak", "latched")

    def __init__(self, name: str, direction: str, warmup: int):
        self.name = name
        self.direction = direction
        self.warmup = max(int(warmup), 4)
        self.window: List[float] = []
        self.median: Optional[float] = None
        self.scale: Optional[float] = None
        self.bad_streak = 0
        self.ok_streak = 0
        self.latched: Optional[dict] = None

    def _badness(self, value: float) -> float:
        z = (value - self.median) / self.scale
        return z if self.direction == "high" else -z

    def observe(self, ts: float, value: float) -> Optional[dict]:
        """Judge one sample; returns a latch/clear event dict or None."""
        if self.median is None:
            self.window.append(value)
            if len(self.window) >= self.warmup:
                self.median = _lower_median(self.window)
                self.scale = _robust_scale(self.window, self.median)
                self.window = []
            return None
        bad = self._badness(value)
        if self.latched is not None:
            if bad < Z_DRIFT:
                self.ok_streak += 1
                if self.ok_streak >= CLEAR_SAMPLES:
                    cleared = self.latched
                    self.latched = None
                    self.ok_streak = 0
                    self.bad_streak = 0
                    return {"event": "clear", "series": self.name,
                            "kind": cleared.get("kind"), "ts": ts,
                            "observed": value}
            else:
                self.ok_streak = 0
            return None
        kind = None
        if bad >= Z_SPIKE:
            kind = "spike"
        elif bad >= Z_DRIFT:
            self.bad_streak += 1
            if self.bad_streak >= DEBOUNCE_SAMPLES:
                kind = "drift"
        else:
            self.bad_streak = 0
        if kind is None:
            return None
        self.bad_streak = 0
        self.ok_streak = 0
        self.latched = {"event": "latch", "series": self.name, "kind": kind,
                        "ts": ts, "observed": value,
                        "baseline": self.median, "z": round(bad, 2)}
        return self.latched


class HealthEngine:
    """Per-rank history rings + online detector + fleet-push payloads.

    ``sample_and_detect()`` is the only producer and runs on the
    MetricsDumper thread (its flush cadence is the sampling cadence);
    readers copy under the lock. Signal collection happens *outside*
    the engine lock — it calls into the perf ledger and the metrics
    registry, and taking their locks under ours would add lock-order
    edges the auditor (HOROVOD_LOCKCHECK) would have to prove out.
    """

    def __init__(self, rank: int = 0, capacity: int = DEFAULT_CAPACITY,
                 warmup: int = DEFAULT_WARMUP, stall_inspector=None,
                 autotuner=None):
        self.rank = rank
        self.capacity = max(int(capacity), 16)
        self.warmup = max(int(warmup), 4)
        self._lock = lockcheck.make_lock("health.ring")
        self._rings: Dict[str, SeriesRing] = {}  # guarded-by: _lock
        self._detectors: Dict[str, _Detector] = {}  # guarded-by: _lock
        self._anomalies_total = 0  # guarded-by: _lock
        self._stall = stall_inspector
        self._autotuner = autotuner
        # perf-ledger read cursor (records_since position == the ledger's
        # total recorded steps); dumper-thread-only
        self._pl_cursor = 0
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._registry = reg
        self._m_samples = reg.counter(
            "hvd_health_samples_total",
            "sampling passes recorded into the health history rings")
        self._m_active = reg.gauge(
            "hvd_health_active_anomalies",
            "anomalies currently latched on this rank")
        self._m_verdict = reg.gauge(
            "hvd_health_verdict",
            "this rank's health verdict: 0 healthy, 1 degraded, 2 critical")
        self._m_anomaly: Dict[tuple, object] = {}

    def attach_stall_inspector(self, inspector) -> None:
        self._stall = inspector

    def attach_autotuner(self, tuner) -> None:
        self._autotuner = tuner

    # -- signal collection --------------------------------------------------
    def _collect(self) -> Dict[str, float]:
        """One value per declared series whose source has data this
        window. Perf-ledger series are windowed over the records since
        the last pass; registry reads are non-creating, so a feature
        that is off contributes nothing (and registers nothing)."""
        vals: Dict[str, float] = {}
        from . import perfledger as perfledger_mod

        ledger = perfledger_mod.get_ledger()
        if ledger is not None:
            self._pl_cursor, recs = ledger.records_since(self._pl_cursor)
            if recs:
                n = len(recs)
                sum_wall = sum(r["wall_s"] for r in recs)
                sum_round = sum(r["negotiate_s"] + r["stall_s"] for r in recs)
                sum_stall = sum(r["stall_s"] for r in recs)
                vals["step_time_ms"] = sum_wall / n * 1e3
                vals["negotiate_ms"] = sum_round / n * 1e3
                if sum_wall > 0:
                    vals["exposed_comm_frac"] = sum_round / sum_wall
                    vals["stall_share"] = sum_stall / sum_wall
                hits = sum(r.get("plan_hits", 0.0) for r in recs)
                misses = sum(r.get("plan_misses", 0.0) for r in recs)
                if hits + misses > 0:
                    vals["plan_hit_rate"] = hits / (hits + misses)
                vals["wire_bytes_per_step"] = (
                    sum(r.get("wire_bytes", 0.0) for r in recs) / n)
        reg = self._registry
        wait_p95 = reg.histogram_quantile("hvd_straggler_wait_seconds", 0.95)
        if wait_p95 is not None:
            vals["straggler_wait_ms"] = wait_p95 * 1e3
        mp_active = reg.gauge_value("hvd_megaplan_active")
        if mp_active is not None:
            vals["megaplan_active"] = mp_active
        peak = reg.gauge_value("hvd_mem_peak_bytes")
        if peak is not None:
            vals["mem_peak_bytes"] = peak
        last_ckpt = reg.gauge_value("hvd_ckpt_last_step")
        if last_ckpt is not None and ledger is not None:
            vals["ckpt_lag_steps"] = max(
                float(self._pl_cursor) - last_ckpt, 0.0)
        return vals

    # -- the dump-cadence hook ----------------------------------------------
    def sample_and_detect(self) -> List[dict]:
        """One sampling + detection pass (MetricsDumper flush cadence).
        Returns the latch/clear events of this pass (tests poll it)."""
        vals = self._collect()
        now = time.time()
        events: List[dict] = []
        with self._lock:
            for name, value in vals.items():
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = SeriesRing(self.capacity)
                    self._detectors[name] = _Detector(
                        name, DIRECTIONS[name], self.warmup)
                value = float(value)
                ring.append(now, value)
                event = self._detectors[name].observe(now, value)
                if event is not None:
                    events.append(event)
                    if event["event"] == "latch":
                        self._anomalies_total += 1
            active = [dict(d.latched) for d in self._detectors.values()
                      if d.latched is not None]
        self._m_samples.inc()
        self._m_active.set(len(active))
        self._m_verdict.set(_VERDICT_LEVELS[_local_verdict(len(active))])
        for event in events:
            if event["event"] == "latch":
                self._fire(event)
            else:
                flightrec_mod.note("health", event="clear",
                                   series=event["series"],
                                   kind=event["kind"], rank=self.rank)
        return events

    def _fire(self, anomaly: dict) -> None:
        """Escalate one freshly latched anomaly (outside the ring lock)."""
        series, kind = anomaly["series"], anomaly["kind"]
        key = (series, kind)
        counter = self._m_anomaly.get(key)
        if counter is None:
            counter = self._registry.counter(
                "hvd_health_anomaly_total",
                "drift/spike anomalies latched by the online detector "
                "(once per episode)", series=series, kind=kind)
            self._m_anomaly[key] = counter
        counter.inc()
        flightrec_mod.note("health", event="latch", series=series, kind=kind,
                           observed=round(anomaly["observed"], 6),
                           baseline=round(anomaly["baseline"], 6),
                           z=anomaly["z"], rank=self.rank)
        detail = (f"{anomaly['observed']:.4g} vs baseline "
                  f"{anomaly['baseline']:.4g} (z={anomaly['z']:g}, "
                  f"kind={kind})")
        inspector = self._stall
        if inspector is not None:
            inspector.note_health_anomaly(series, detail)
        else:
            LOG.warning("Health anomaly on %r: %s.", series, detail)
        if series in AUTOTUNE_SERIES and kind == "drift":
            tuner = self._autotuner
            if tuner is not None:
                try:
                    tuner.note_health_drift(series)
                except Exception as e:  # telemetry must not take the job down
                    LOG.debug("health->autotune re-tune hook failed: %s", e)

    # -- views --------------------------------------------------------------
    def _suspect_rank(self) -> Optional[int]:
        inspector = self._stall
        if inspector is None:
            return None
        getter = getattr(inspector, "straggler_rank", None)
        return getter() if getter is not None else None

    def active_anomalies(self) -> List[dict]:
        with self._lock:
            return [dict(d.latched) for d in self._detectors.values()
                    if d.latched is not None]

    def snapshot(self) -> dict:
        """Push payload for ``health/rank{k}`` — bounded: the newest
        ``PUSH_WINDOW`` raw + downsampled points per series, the active
        anomalies, and the learned baselines. The full rings stay local
        (``history()`` / the on-exit file dump)."""
        with self._lock:
            series = {
                name: {"n": ring.total,
                       "samples": [[round(ts, 3), round(v, 6)]
                                   for ts, v in list(ring.raw)[-PUSH_WINDOW:]],
                       "downsampled": [[round(ts, 3), round(v, 6)]
                                       for ts, v in
                                       list(ring.tier)[-PUSH_WINDOW:]]}
                for name, ring in sorted(self._rings.items())}
            active = [dict(d.latched) for d in self._detectors.values()
                      if d.latched is not None]
            baselines = _baselines(self._detectors)
            total = self._anomalies_total
        return {"rank": self.rank, "verdict": _local_verdict(len(active)),
                "active": active, "baselines": baselines,
                "anomalies_total": total, "series": series,
                "suspect_rank": self._suspect_rank()}

    def history(self, series=None, since: float = 0.0) -> dict:
        """Windowed query over the *full* local rings (the ``GET
        /history`` shape for one rank; also the on-exit dump body).
        ``series`` is an optional iterable of names; ``since`` drops
        points older than the given unix timestamp."""
        wanted = set(series) if series else None
        with self._lock:
            out_series = {}
            for name, ring in sorted(self._rings.items()):
                if wanted is not None and name not in wanted:
                    continue
                out_series[name] = {
                    "n": ring.total,
                    "samples": [[round(ts, 3), round(v, 6)]
                                for ts, v in ring.raw if ts >= since],
                    "downsampled": [[round(ts, 3), round(v, 6)]
                                    for ts, v in ring.tier if ts >= since]}
            active = [dict(d.latched) for d in self._detectors.values()
                      if d.latched is not None]
            baselines = _baselines(self._detectors)
            total = self._anomalies_total
        return {"rank": self.rank, "verdict": _local_verdict(len(active)),
                "active": active, "baselines": baselines,
                "anomalies_total": total, "series": out_series}

    def report(self) -> dict:
        """``hvd.health_report()`` body for this rank."""
        with self._lock:
            series = {name: {"n": ring.total,
                             "last": round(ring.raw[-1][1], 6)
                             if ring.raw else None}
                      for name, ring in sorted(self._rings.items())}
            active = [dict(d.latched) for d in self._detectors.values()
                      if d.latched is not None]
            baselines = _baselines(self._detectors)
            total = self._anomalies_total
        return {"enabled": True, "rank": self.rank,
                "verdict": _local_verdict(len(active)),
                "active": active, "anomalies_total": total,
                "baselines": baselines, "series": series,
                "capacity": self.capacity, "warmup": self.warmup,
                "suspect_rank": self._suspect_rank() if active else None}

    def dump_file(self, path: str) -> None:
        """Atomic full-history dump (tmp + rename, the utils/checkpoint
        convention): the ``HOROVOD_HEALTH_FILE`` on-exit artifact,
        renderable by ``tools/benchtrend --from-history``."""
        doc = self.history()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)


def _local_verdict(active_count: int) -> str:
    if active_count == 0:
        return "healthy"
    if active_count < CRITICAL_ANOMALIES:
        return "degraded"
    return "critical"


def fleet_view(ranks: Dict[str, dict]) -> dict:
    """The ``GET /health`` body: one fleet verdict from merged per-rank
    snapshots, with suspects ranked by cross-rank outlier score.

    Scoring: per declared series, each rank's newest sample is judged by
    robust z against the fleet's lower-median baseline (direction-aware,
    only bad-direction excursions count); every anomaly a rank's own
    detector latched adds ``ANOMALY_SUSPECT_WEIGHT`` — a rank that
    *knows* it regressed outranks one that merely reads high this
    window; and each anomalous rank's coordinator straggler attribution
    (``suspect_rank``) adds ``STRAGGLER_SUSPECT_WEIGHT`` to the rank it
    names — and the naming COUNT is the primary sort key: a lockstep
    delay slows every rank, and a victim stuck at the barrier often
    reads *higher* z than the culprit, so the coordinator's
    last-to-submit verdict, not the magnitudes, is what separates the
    culprit from the waiting victims (score orders ranks with equal
    namings). Pure function (no engine needed) so the launcher can
    serve it and tests can drive it directly."""
    worst = "healthy"
    anomalies: List[dict] = []
    scores = {rank: 0.0 for rank in ranks}
    namings = {rank: 0 for rank in ranks}
    contrib: Dict[str, dict] = {rank: {} for rank in ranks}
    for rank, snap in ranks.items():
        if not isinstance(snap, dict):
            continue
        verdict = snap.get("verdict")
        if _VERDICT_LEVELS.get(verdict, 0) > _VERDICT_LEVELS[worst]:
            worst = verdict
        active = [a for a in (snap.get("active") or [])
                  if isinstance(a, dict)]
        for a in active:
            anomalies.append(dict(a, rank=rank))
        if active:
            scores[rank] += ANOMALY_SUSPECT_WEIGHT * len(active)
            contrib[rank]["active_anomalies"] = len(active)
            named = snap.get("suspect_rank")
            if isinstance(named, int) and str(named) in scores:
                namings[str(named)] += 1
                scores[str(named)] += STRAGGLER_SUSPECT_WEIGHT
                contrib[str(named)]["named_straggler"] = round(
                    contrib[str(named)].get("named_straggler", 0.0)
                    + STRAGGLER_SUSPECT_WEIGHT, 3)
    for name, direction, _ in SERIES:
        last: Dict[str, float] = {}
        for rank, snap in ranks.items():
            if not isinstance(snap, dict):
                continue
            body = (snap.get("series") or {}).get(name)
            samples = body.get("samples") if isinstance(body, dict) else None
            if not samples:
                continue
            point = samples[-1]
            if isinstance(point, (list, tuple)) and len(point) == 2 \
                    and isinstance(point[1], (int, float)):
                last[rank] = float(point[1])
        if len(last) < 2:
            continue
        median = _lower_median(list(last.values()))
        scale = _robust_scale(list(last.values()), median)
        for rank, value in last.items():
            z = (value - median) / scale
            bad = z if direction == "high" else -z
            if bad > 0:
                scores[rank] += bad
                contrib[rank][name] = round(bad, 3)
    suspects = [{"rank": rank, "score": round(score, 3),
                 "series": contrib[rank]}
                for rank, score in sorted(
                    scores.items(),
                    key=lambda kv: (-namings[kv[0]], -kv[1], kv[0]))
                if score > 0]
    if len(anomalies) >= CRITICAL_ANOMALIES \
            and _VERDICT_LEVELS[worst] < _VERDICT_LEVELS["critical"]:
        worst = "critical"
    return {
        "verdict": worst,
        "suspects": suspects,
        "anomalies": anomalies,
        "ranks": {rank: {"verdict": snap.get("verdict"),
                         "stale": bool(snap.get("stale", False)),
                         "active": len(snap.get("active") or []),
                         "anomalies_total": snap.get("anomalies_total")}
                  for rank, snap in ranks.items()
                  if isinstance(snap, dict)},
        "baselines": {rank: snap.get("baselines")
                      for rank, snap in ranks.items()
                      if isinstance(snap, dict)},
    }


# --------------------------------------------------------------------------
# Process-global engine (the utils/perfledger.py module-trio pattern):
# get_engine() returns None when HOROVOD_HEALTH is off, and the hook site
# (MetricsDumper.flush) costs exactly one is-None check in that state.
# --------------------------------------------------------------------------

_ENGINE: Optional[HealthEngine] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_HEALTH)


def get_engine() -> Optional[HealthEngine]:
    return _ENGINE


def init_engine(rank: int = 0, stall_inspector=None,
                autotuner=None) -> Optional[HealthEngine]:
    """Create the process engine when ``HOROVOD_HEALTH`` is set
    (idempotent, like perfledger's init_ledger); no-op returning None
    when off. Later calls hand over the stall inspector / autotuner once
    those exist — context.init() wires them in its own order."""
    global _ENGINE
    if not enabled():
        return _ENGINE
    if _ENGINE is None:
        capacity = env_schema.get_int(env_schema.HOROVOD_HEALTH_BUFFER,
                                      DEFAULT_CAPACITY)
        warmup = env_schema.get_int(env_schema.HOROVOD_HEALTH_WARMUP,
                                    DEFAULT_WARMUP)
        _ENGINE = HealthEngine(rank=rank, capacity=capacity, warmup=warmup,
                               stall_inspector=stall_inspector,
                               autotuner=autotuner)
    if stall_inspector is not None:
        _ENGINE.attach_stall_inspector(stall_inspector)
    if autotuner is not None:
        _ENGINE.attach_autotuner(autotuner)
    return _ENGINE


def reset_engine() -> None:
    """Drop the process engine (test/bench helper)."""
    global _ENGINE
    _ENGINE = None


def dump_on_exit() -> None:
    """Write the full history rings to ``HOROVOD_HEALTH_FILE`` if both
    the engine and the knob are set (context.shutdown(), after the
    dumper's final flush so the file carries the last sampled window)."""
    engine = _ENGINE
    if engine is None:
        return
    path = env_schema.get_str(env_schema.HOROVOD_HEALTH_FILE)
    if not path:
        return
    try:
        engine.dump_file(path)
    except OSError as e:
        LOG.warning("health history dump failed: %s", e)


def report() -> dict:
    """``hvd.health_report()`` body: ``{"enabled": False}`` when the
    engine is off, else this rank's verdict, active anomalies, learned
    baselines, and per-series history heads."""
    engine = _ENGINE
    if engine is None:
        return {"enabled": False}
    return engine.report()
