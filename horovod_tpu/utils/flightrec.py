"""Flight recorder: a bounded, lock-cheap ring of control-plane events.

The metrics registry answers "how much/how fast" and the tracer answers
"when did each collective run", but neither answers the postmortem
question "what was the control plane *doing* right before it stopped?"
— every wedged bench round so far (BENCH_r01–r05) died with zero record
of the last init phase reached, negotiation round opened, retry fired,
or fault injected. This module is that record: an always-cheap
append-only ring of structured events (monotonic + wall timestamps,
category, rank, free-form kv fields) that the diagnostics bundle
(utils/diag.py) snapshots at the moment of a hang, crash, or signal.

Categories are a closed registry (:data:`CATEGORIES`): hvdlint's
event-names rule checks every ``note("<category>", ...)`` call site
against it and requires each category to be snake_case, unique, and
documented in docs/observability.md — the same contract metric names
live under.

Zero-cost contract (same as utils/tracing.py, enforced by hvdlint's
zero-cost-hooks rule and benchmarks/flightrec_overhead.py): with
``HOROVOD_FLIGHTREC`` unset no recorder exists, hot paths pay one
``is None`` check per hook, and no ``hvd_flightrec_*`` series is
registered. Metric handles are resolved in ``FlightRecorder.__init__``
— lazily at enable — so the off state adds zero series.
"""

from __future__ import annotations

import collections
import time
from typing import List, Optional

from ..common import env as env_schema
from . import lockcheck

#: The closed event-category registry: (name, meaning). hvdlint parses
#: this tuple (tools/hvdlint/core.py) the way it parses faults.py SITES;
#: add a row here (and a docs/observability.md mention) before noting a
#: new category anywhere.
CATEGORIES = (
    ("init_phase", "hvd.init() milestone reached"),
    ("negotiation_round", "controller negotiation round begin/end"),
    ("elastic_generation", "elastic discovery epoch/generation change"),
    ("retry_attempt", "control-plane retry about to back off"),
    ("fault_injected", "chaos fault fired at an instrumented site"),
    ("plan_cache_invalidated", "compiled fused-chunk plans dropped"),
    ("reshard", "sharded-update layout (re)built"),
    ("probe_verdict", "backend liveness probe decided"),
    ("watchdog", "wedge watchdog fired"),
    ("diag_dump", "diagnostic bundle written"),
    ("quant_fallback", "tensor kept off the quantized wire"),
    ("slo_breach", "declared SLO budget crossed its bound"),
    ("compile", "XLA program compiled for a cached plan"),
    ("leader_round", "node-leader negotiation round merged or fell back"),
    ("autotune_step", "autotuner proposed/applied/reverted a config"),
    ("checkpoint", "async checkpoint snapshot/flush/restore lifecycle"),
    ("megaplan", "whole-step schedule captured/replayed/invalidated"),
    ("health", "fleet-health anomaly latched or cleared on a drifted series"),
)

CATEGORY_NAMES = frozenset(name for name, _ in CATEGORIES)

DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Bounded structured event ring, safe to write from any thread.

    ``note()`` is the only hot method: one tuple build plus a deque
    append under a short lock. Readers (:meth:`events`) copy the ring
    under the same lock, so a watchdog dump mid-flight sees a clean cut.
    """

    def __init__(self, rank: int = 0, capacity: int = DEFAULT_CAPACITY):
        self.rank = rank
        self.capacity = max(int(capacity), 16)
        self._lock = lockcheck.make_lock("flightrec.ring")
        self._ring = collections.deque(maxlen=self.capacity)  # guarded-by: _lock
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._m_events = reg.counter(
            "hvd_flightrec_events_total", "flight-recorder events noted")
        self._m_dropped = reg.counter(
            "hvd_flightrec_dropped_total",
            "flight-recorder events evicted by ring wraparound")

    def note(self, category: str, **kv) -> None:
        """Append one event. ``kv`` must be JSON-able scalars (the bundle
        serializes the ring); callers keep payloads tiny — this is a
        breadcrumb trail, not a log."""
        ev = (time.monotonic(), time.time(), category, kv)
        with self._lock:
            dropped = len(self._ring) == self.capacity
            self._ring.append(ev)
        self._m_events.inc()
        if dropped:
            self._m_dropped.inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self, last: Optional[int] = None) -> List[dict]:
        """The ring's contents, oldest first, as JSON-able dicts
        (``last`` keeps only the newest N)."""
        with self._lock:
            evs = list(self._ring)
        if last is not None:
            evs = evs[-int(last):]
        return [{"ts_mono": mono, "ts": wall, "cat": cat,
                 "rank": self.rank, "kv": kv}
                for mono, wall, cat, kv in evs]

    def snapshot(self, last: int = 200) -> dict:
        """Push/bundle payload: rank + the newest ``last`` events."""
        return {"rank": self.rank, "events": self.events(last=last)}


# --------------------------------------------------------------------------
# Process-global recorder (the utils/tracing.py module-trio pattern):
# get_recorder() returns None when HOROVOD_FLIGHTREC is off, and every
# hook site costs exactly one is-None check in that state.
# --------------------------------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_FLIGHTREC)


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def init_recorder(rank: int = 0) -> Optional[FlightRecorder]:
    """Create the process recorder when ``HOROVOD_FLIGHTREC`` is set
    (idempotent: reuses a live recorder so init/shutdown cycles keep one
    continuous ring); no-op returning None when off."""
    global _RECORDER
    if not enabled():
        return _RECORDER
    if _RECORDER is None:
        capacity = env_schema.get_int(env_schema.HOROVOD_FLIGHTREC_BUFFER,
                                      DEFAULT_CAPACITY)
        _RECORDER = FlightRecorder(rank=rank, capacity=capacity)
    return _RECORDER


def reset_recorder() -> None:
    """Drop the process recorder (test/bench helper)."""
    global _RECORDER
    _RECORDER = None


def note(category: str, **kv) -> None:
    """Cold-path convenience: record an event iff the recorder is on.

    Hot paths (ops/queue.py) resolve the handle once at construction
    instead; this wrapper is for the sites that fire rarely (retries,
    faults, elastic transitions, probe verdicts)."""
    recorder = _RECORDER
    if recorder is None:
        return
    recorder.note(category, **kv)
