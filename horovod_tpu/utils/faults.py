"""Fault-injection ("chaos") layer for the control plane.

The reference Horovod's resilience machinery (stall inspector,
elastic blacklist-and-restart) is only ever exercised by *production*
failures; there is no way to provoke a dropped socket, a slow poll, or a
preempted worker deterministically in a test. This module is that missing
piece: named **fault points** sit at the control-plane seams (KV
get/put/wait, controller poll/submit, elastic spawn/heartbeat, metrics
push) and are driven entirely by one env knob::

    HOROVOD_FAULT_SPEC="kv.get:drop#1,controller.poll:delay=250ms@0.5,elastic.spawn:fail#1"

Spec grammar — comma-separated entries, each::

    site:mode[=arg][@gate][#count]

- ``site``  — fault-point name (see SITES below for the instrumented set).
- ``mode``  — ``drop``  (raise a connection-level error, as if the peer
  vanished mid-exchange), ``delay`` (sleep ``arg``, default 50 ms; accepts
  ``5s`` / ``250ms`` / bare seconds), ``error``/``fail`` (raise
  ``FaultInjectedError``; ``arg`` is the message), ``torn`` (truncate a
  payload at a write site — exercised via :func:`corrupt`).
- ``@gate`` — when to fire: a float ``<= 1`` is a per-hit probability
  (deterministic: drawn from an RNG seeded by ``HOROVOD_FAULT_SEED`` +
  site + rank, so a failing chaos run replays exactly); an integer ``> 1``
  fires on every Nth hit. Default: every hit.
- ``#count`` — total trigger budget (default unlimited).
  ``elastic.spawn:fail#1`` fails exactly the first spawn, then heals —
  the shape of a transient SSH/preemption blip.

Unconfigured, every fault point is an inert no-op (one env-dict lookup),
and no ``hvd_fault_*`` metric exists in the registry; each *trigger*
increments ``hvd_fault_injected_total{site,mode}``.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Optional

from ..common import env as env_schema
from ..common.exceptions import FaultInjectedError

LOG = logging.getLogger("horovod_tpu")

HOROVOD_FAULT_SPEC = "HOROVOD_FAULT_SPEC"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"

#: Instrumented fault-point names (documentation + spec validation aid).
SITES = (
    "kv.get", "kv.put", "kv.wait", "kv.delete",
    "controller.poll", "controller.submit",
    "leader.merge",
    "elastic.spawn", "elastic.heartbeat",
    "metrics.push",
    "autotune.propose",
    "plan.dispatch",
    "ckpt.write", "ckpt.flush",
    "megaplan.capture", "megaplan.replay",
    "health.sample",
)

MODES = ("drop", "delay", "error", "fail", "torn")


class FaultInjectedConnectionError(FaultInjectedError, ConnectionError):
    """Injected connection-level fault (``drop`` mode): an OSError
    subclass, so transport-layer retry policies classify it exactly like
    a real dropped socket."""


def _parse_duration(s: str) -> float:
    s = s.strip().lower()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


class _Rule:
    """One parsed spec entry with its trigger state (hits / budget)."""

    def __init__(self, site: str, mode: str, arg: str,
                 gate: Optional[str], count: Optional[int], seed: int):
        self.site = site
        self.mode = "error" if mode == "fail" else mode
        if self.mode not in ("drop", "delay", "error", "torn"):
            raise ValueError(f"unknown fault mode {mode!r}")
        self.arg = arg
        self.delay_s = _parse_duration(arg) if self.mode == "delay" and arg \
            else 0.05
        self.probability: Optional[float] = None
        self.every_nth: Optional[int] = None
        if gate is not None:
            g = float(gate)
            if g <= 1.0:
                self.probability = g
            else:
                self.every_nth = int(g)
        self.remaining = count  # None = unlimited
        self.hits = 0
        # deterministic per-(seed, site, rank) stream: a failing chaos run
        # replays bit-for-bit, and ranks draw distinct sequences
        rank = os.environ.get(env_schema.HOROVOD_RANK, "0")
        self._rng = random.Random(f"{seed}:{site}:{mode}:{rank}")
        self._lock = threading.Lock()
        self._metric = None

    def should_fire(self) -> bool:
        with self._lock:
            self.hits += 1
            if self.remaining is not None and self.remaining <= 0:
                return False
            if self.probability is not None:
                if self._rng.random() >= self.probability:
                    return False
            elif self.every_nth is not None:
                if self.hits % self.every_nth != 0:
                    return False
            if self.remaining is not None:
                self.remaining -= 1
            return True

    def record(self):
        # lazily registered so an unconfigured run registers NO
        # hvd_fault_* series at all (acceptance criterion)
        if self._metric is None:
            from . import metrics as metrics_mod

            self._metric = metrics_mod.get_registry().counter(
                "hvd_fault_injected_total", "chaos faults injected",
                site=self.site, mode=self.mode)
        self._metric.inc()
        from . import flightrec

        flightrec.note("fault_injected", site=self.site, mode=self.mode)

    def fire(self):
        self.record()
        if self.mode == "delay":
            LOG.debug("fault %s: injected %.3fs delay", self.site,
                      self.delay_s)
            time.sleep(self.delay_s)
        elif self.mode == "drop":
            raise FaultInjectedConnectionError(
                f"injected connection drop at fault point {self.site!r} "
                f"(HOROVOD_FAULT_SPEC)")
        elif self.mode == "error":
            raise FaultInjectedError(
                self.arg or f"injected error at fault point {self.site!r} "
                            f"(HOROVOD_FAULT_SPEC)")
        # "torn" only acts through corrupt()


class _FaultState:
    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.rules: dict[str, list[_Rule]] = {}
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            site, _, rest = entry.partition(":")
            site = site.strip()
            if not rest:
                raise ValueError(
                    f"bad HOROVOD_FAULT_SPEC entry {entry!r}: want "
                    "site:mode[=arg][@gate][#count]")
            count: Optional[int] = None
            if "#" in rest:
                rest, _, c = rest.rpartition("#")
                count = int(c)
            gate: Optional[str] = None
            if "@" in rest:
                rest, _, gate = rest.rpartition("@")
            mode, _, arg = rest.partition("=")
            self.rules.setdefault(site, []).append(
                _Rule(site, mode.strip(), arg.strip(), gate, count, seed))


_state: Optional[_FaultState] = None
_state_lock = threading.Lock()


def _active() -> Optional[_FaultState]:
    spec = os.environ.get(HOROVOD_FAULT_SPEC, "")
    if not spec:
        return None
    global _state
    st = _state
    if st is not None and st.spec == spec:
        return st
    with _state_lock:
        if _state is None or _state.spec != spec:
            try:
                _state = _FaultState(
                    spec, int(os.environ.get(HOROVOD_FAULT_SEED, "0") or 0))
            except ValueError as e:
                # a malformed spec must not take the job down — chaos
                # tooling is opt-in observability, loud but harmless
                LOG.error("ignoring malformed %s=%r: %s",
                          HOROVOD_FAULT_SPEC, spec, e)
                _state = _FaultState("", 0)
                _state.spec = spec  # cache the rejection
        return _state


def reset():
    """Drop parsed spec state (test helper: re-arm trigger budgets)."""
    global _state
    with _state_lock:
        _state = None


def fault_point(site: str):
    """Chaos hook: no-op unless ``HOROVOD_FAULT_SPEC`` names ``site``.

    May sleep (``delay``) or raise (``drop`` → connection-level error,
    ``error`` → :class:`FaultInjectedError`). Call it at the top of the
    operation the fault should hit, inside any retry scope that is
    supposed to absorb it.
    """
    st = _active()
    if st is None:
        return
    for rule in st.rules.get(site, ()):
        if rule.mode != "torn" and rule.should_fire():
            rule.fire()


def corrupt(site: str, data: bytes) -> bytes:
    """Torn-write hook for payload-carrying sites: returns ``data``
    truncated to half its length when a ``torn`` rule fires (the
    half-written value a crashed writer leaves behind), else unchanged."""
    st = _active()
    if st is None:
        return data
    for rule in st.rules.get(site, ()):
        if rule.mode == "torn" and rule.should_fire():
            rule.record()
            LOG.debug("fault %s: torn write (%d -> %d bytes)", site,
                      len(data), len(data) // 2)
            return data[: len(data) // 2]
    return data
