"""Autotuner for eager-runtime parameters.

Reference: /root/reference/horovod/common/parameter_manager.{h,cc} +
common/optim/bayesian_optimization.cc — Bayesian optimization (GP + expected
improvement) over fusion-threshold and cycle-time, scored in bytes/sec, with
the winning parameters broadcast from the coordinator
(Controller::SynchronizeParameters, controller.cc:39-53).

On TPU the compiled path needs no tuning (XLA schedules), so the search
space here is the *eager* runtime's fusion threshold and cycle time, plus
the gradient-bucket size used by `horovod_tpu.opt` bucketing. Round-1
implementation is a coordinate-descent hill climber over a log-scaled grid
(the reference's categorical/continuous split, parameter_manager.h:186);
scores are smoothed bytes/sec from `BackgroundRuntime` counters. A GP-EI
upgrade can drop in behind the same `Autotuner.sample()` API.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

LOG = logging.getLogger("horovod_tpu")

_FUSION_GRID = [1 << 20, 4 << 20, 16 << 20, 64 << 20, 128 << 20, 256 << 20]
_CYCLE_GRID = [0.5, 1.0, 2.5, 5.0, 10.0, 25.0]


class Autotuner:
    def __init__(self, runtime, log_path: str = "", warmup_samples: int = 3):
        self.runtime = runtime
        self.log_path = log_path
        self.warmup = warmup_samples
        self._samples = 0
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self._best_score = 0.0
        self._tuning_axis = 0  # 0=fusion, 1=cycle
        self._fusion_i = _FUSION_GRID.index(min(_FUSION_GRID,
                                                key=lambda v: abs(v - runtime.fusion_threshold)))
        self._cycle_i = _CYCLE_GRID.index(min(_CYCLE_GRID,
                                              key=lambda v: abs(v - runtime.cycle_time_ms)))
        self._direction = 1
        self.done = False
        if log_path:
            with open(log_path, "w") as f:
                f.write("sample,fusion_bytes,cycle_ms,score_bytes_per_sec\n")

    def sample(self):
        """Record one scoring sample and maybe move a knob. Call periodically
        (e.g. once per training step or per N cycles)."""
        if self.done:
            return
        now = time.monotonic()
        dt = now - self._last_time
        if dt <= 0:
            return
        db = self.runtime.bytes_processed - self._last_bytes
        score = db / dt
        self._last_bytes = self.runtime.bytes_processed
        self._last_time = now
        self._samples += 1
        if self.log_path:
            with open(self.log_path, "a") as f:
                f.write(f"{self._samples},{self.runtime.fusion_threshold},"
                        f"{self.runtime.cycle_time_ms},{score:.1f}\n")
        if self._samples <= self.warmup:
            self._best_score = max(self._best_score, score)
            return
        if score >= self._best_score * 1.02:
            self._best_score = score  # keep moving in this direction
        else:
            # revert / switch axis (coordinate descent)
            self._direction = -self._direction
            self._tuning_axis = 1 - self._tuning_axis
            if self._tuning_axis == 0 and self._direction == 1:
                self.done = True
                LOG.info("autotune converged: fusion=%d cycle=%.2fms",
                         self.runtime.fusion_threshold, self.runtime.cycle_time_ms)
                return
        if self._tuning_axis == 0:
            self._fusion_i = min(max(self._fusion_i + self._direction, 0),
                                 len(_FUSION_GRID) - 1)
            self.runtime.fusion_threshold = _FUSION_GRID[self._fusion_i]
        else:
            self._cycle_i = min(max(self._cycle_i + self._direction, 0),
                                len(_CYCLE_GRID) - 1)
            self.runtime.cycle_time_ms = _CYCLE_GRID[self._cycle_i]
