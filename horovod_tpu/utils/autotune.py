"""Autotuner for eager-runtime parameters — synchronized Bayesian search.

Reference: /root/reference/horovod/common/parameter_manager.{h,cc} +
common/optim/bayesian_optimization.cc + gaussian_process.cc — Bayesian
optimization (Gaussian process + expected improvement) over
fusion-threshold and cycle-time, scored in bytes/sec, with the winning
parameters broadcast from the coordinator so every rank always runs the
same knobs (Controller::SynchronizeParameters, controller.cc:39-53 —
per-rank divergence would change fused-program signatures across ranks).

On TPU the compiled path needs no tuning (XLA schedules); the search space
is the *eager* runtime's fusion threshold and cycle time. Design:

- Rank 0 owns the GP: it scores its own smoothed bytes/sec (symmetric in
  data-parallel steady state), observes (params, score) pairs, and proposes
  the next point by maximizing expected improvement over log-scaled bounds.
- Proposals ride the negotiated RESPONSE (KVController.submit_params →
  runtime._apply_tuned_params): every rank — rank 0 included — applies
  them at response receipt, the same round boundary everywhere. This is
  load-bearing for the hierarchical knobs, which change the XLA program
  built for a negotiated tensor. After ``max_samples`` the best observed
  point rides a final response and tuning stops everywhere.
- Single-process (no controller): same GP, applied locally.

The GP here is an original small implementation: RBF kernel, fixed noise,
Cholesky solve, EI acquisition maximized over a quasi-random candidate set
(the role of the reference's L-BFGS ascent on the acquisition).
"""

from __future__ import annotations

import logging
import math
import time
from typing import Optional

import numpy as np

from . import metrics as metrics_mod

LOG = logging.getLogger("horovod_tpu")

# log2-space bounds: fusion 1 MiB .. 256 MiB, cycle 0.5 .. 25 ms.
# Dims 2-3 are the categorical knobs the reference's ParameterManager
# also tunes (parameter_manager.h:42 hierarchical allreduce/allgather):
# relaxed to [0,1] in the GP and thresholded at 0.5 when applied — the
# continuous relaxation plays the role of the reference's categorical
# grid, sharing one surrogate across both settings.
_BOUNDS = np.array([[20.0, 28.0],
                    [math.log2(0.5), math.log2(25.0)]])
_DIMS = 4


class _GP:
    """Minimal RBF-kernel Gaussian process (reference gaussian_process.cc
    role), inputs normalized to [0,1]^d."""

    def __init__(self, length_scale: float = 0.25, noise: float = 1e-3):
        self.ls = length_scale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._alpha = None
        self._L = None

    def _k(self, A, B):
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray):
        self._X = X
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))

    def predict(self, Xs: np.ndarray):
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


def _expected_improvement(mu, sigma, best, xi: float = 0.01):
    """EI acquisition (reference bayesian_optimization.cc:ExpectedImprovement
    semantics, original formula implementation)."""
    z = (mu - best - xi) / sigma
    # standard normal pdf/cdf without scipy
    pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    return (mu - best - xi) * cdf + sigma * pdf


class BayesianOptimizer:
    """Propose points in normalized [0,1]^d maximizing EI; first
    ``n_random`` proposals are low-discrepancy random exploration."""

    def __init__(self, dims: int = 2, n_random: int = 4, seed: int = 0):
        self.dims = dims
        self.n_random = n_random
        self.rng = np.random.RandomState(seed)
        self.X: list[np.ndarray] = []
        self.y: list[float] = []

    def observe(self, x: np.ndarray, score: float):
        self.X.append(np.asarray(x, float))
        self.y.append(float(score))

    def suggest(self) -> np.ndarray:
        if len(self.X) < self.n_random:
            return self.rng.uniform(size=self.dims)
        X = np.stack(self.X)
        y = np.asarray(self.y)
        scale = y.std() or 1.0
        gp = _GP()
        gp.fit(X, (y - y.mean()) / scale)
        cand = self.rng.uniform(size=(256, self.dims))
        mu, sigma = gp.predict(cand)
        ei = _expected_improvement(mu, sigma, (y.max() - y.mean()) / scale)
        return cand[int(np.argmax(ei))]

    def best(self) -> Optional[np.ndarray]:
        if not self.X:
            return None
        return self.X[int(np.argmax(self.y))]


def _to_params(x01: np.ndarray) -> tuple[int, float, bool, bool]:
    lo, hi = _BOUNDS[:, 0], _BOUNDS[:, 1]
    logs = lo + np.clip(x01[:2], 0, 1) * (hi - lo)
    return (int(2.0 ** logs[0]), float(2.0 ** logs[1]),
            bool(x01[2] >= 0.5), bool(x01[3] >= 0.5))


def _from_params(fusion: int, cycle: float,
                 hier_ar: bool, hier_ag: bool) -> np.ndarray:
    lo, hi = _BOUNDS[:, 0], _BOUNDS[:, 1]
    logs = np.array([math.log2(max(fusion, 1)), math.log2(max(cycle, 1e-3))])
    cont = np.clip((logs - lo) / (hi - lo), 0, 1)
    return np.concatenate([cont, [0.75 if hier_ar else 0.25,
                                  0.75 if hier_ag else 0.25]])


class Autotuner:
    """Scores smoothed bytes/sec and drives the synchronized search.

    ``sample()`` is called from the background cycle loop every N working
    cycles on every rank; only rank 0 (or a controller-less single process)
    updates the GP and proposes; other ranks apply proposals as they
    arrive on negotiated responses.
    """

    def __init__(self, runtime, log_path: str = "", warmup_samples: int = 3,
                 max_samples: int = 20):
        self.runtime = runtime
        self.log_path = log_path
        self.warmup = warmup_samples
        self.max_samples = max_samples
        self._samples = 0
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self.done = False
        self._final_submitted = False
        ctl = runtime.controller
        self._rank = ctl.rank if ctl is not None else 0
        self._opt = (BayesianOptimizer(dims=_DIMS)
                     if self._rank == 0 else None)
        reg = metrics_mod.get_registry()
        self._m_fusion = reg.gauge("hvd_autotune_fusion_threshold_bytes",
                                   "currently applied fusion threshold")
        self._m_cycle = reg.gauge("hvd_autotune_cycle_time_ms",
                                  "currently applied cycle time")
        self._m_score = reg.gauge("hvd_autotune_last_score_bytes_per_sec",
                                  "last smoothed bytes/sec sample")
        self._m_samples = reg.counter("hvd_autotune_samples_total",
                                      "autotune score samples taken")
        self._m_done = reg.gauge("hvd_autotune_converged",
                                 "1 once the search has converged")
        if log_path:
            with open(log_path, "w") as f:
                f.write("sample,fusion_bytes,cycle_ms,hier_allreduce,hier_allgather,score_bytes_per_sec\n")

    # -- scoring ------------------------------------------------------------
    def _score(self) -> Optional[float]:
        now = time.monotonic()
        dt = now - self._last_time
        if dt <= 0:
            return None
        db = self.runtime.bytes_processed - self._last_bytes
        self._last_bytes = self.runtime.bytes_processed
        self._last_time = now
        return db / dt

    @staticmethod
    def _get_hier() -> tuple[bool, bool]:
        from horovod_tpu.common import context as ctx_mod

        cfg = ctx_mod.context().config
        return cfg.hierarchical_allreduce, cfg.hierarchical_allgather

    @staticmethod
    def _set_hier(hier_ar: bool, hier_ag: bool):
        from horovod_tpu.common import context as ctx_mod

        cfg = ctx_mod.context().config
        cfg.hierarchical_allreduce = bool(hier_ar)
        cfg.hierarchical_allgather = bool(hier_ag)

    def _log(self, score: float):
        self._m_samples.inc()
        self._m_score.set(score)
        self._m_fusion.set(self.runtime.fusion_threshold)
        self._m_cycle.set(self.runtime.cycle_time_ms)
        self._m_done.set(1 if (self.done or self._final_submitted) else 0)
        if self.log_path:
            ar, ag = self._get_hier()
            with open(self.log_path, "a") as f:
                f.write(f"{self._samples},{self.runtime.fusion_threshold},"
                        f"{self.runtime.cycle_time_ms},{int(ar)},{int(ag)},"
                        f"{score:.1f}\n")

    # -- parameter broadcast (SynchronizeParameters, controller.cc:39-53) ---
    def _submit(self, fusion: int, cycle: float, hier_ar: bool,
                hier_ag: bool, final: bool):
        """Hand the proposal to the coordinator: it rides the next
        negotiated response and applies on EVERY rank (this one included)
        at response receipt — never asynchronously, because a per-rank
        divergence in the hierarchical flags would build different XLA
        programs for the same negotiated tensor and corrupt the wire."""
        params = {"fusion": int(fusion), "cycle": float(cycle),
                  "hier_ar": bool(hier_ar), "hier_ag": bool(hier_ag),
                  "final": bool(final)}
        ctl = self.runtime.controller
        if ctl is not None:
            ctl.submit_params(params)
            return
        # through the runtime's setter when it has one (resizes the staging
        # ring and invalidates fused-chunk plans whose boundaries moved);
        # plain attribute set keeps duck-typed runtimes working
        setter = getattr(self.runtime, "set_fusion_threshold", None)
        if setter is not None:
            setter(params["fusion"])
        else:
            self.runtime.fusion_threshold = params["fusion"]
        self.runtime.cycle_time_ms = params["cycle"]
        ps = getattr(self.runtime, "process_set", None)
        if ps is None or ps.cross_size == 1:
            # truly single process: no lockstep to protect
            self._set_hier(params["hier_ar"], params["hier_ag"])
        # else: multi-process WITHOUT a rendezvous store (name-ordered
        # fallback) — every rank tunes its own fusion/cycle locally
        # (survivable: the coordinator-less path doesn't fuse across
        # ranks), but the hierarchical flags change the XLA program
        # shape and MUST NOT diverge, so they stay untouched here
        if final:
            self.done = True

    # -- main entry ---------------------------------------------------------
    def sample(self):
        if self._rank != 0:
            # params arrive via the negotiated response
            # (runtime._apply_tuned_params); nothing to poll
            score = self._score()
            if score is not None:
                self._samples += 1
                self._log(score)
            return
        if self.done or self._final_submitted:
            return
        score = self._score()
        if score is None:
            return
        self._samples += 1
        self._log(score)
        if self._samples <= self.warmup:
            return
        ar_now, ag_now = self._get_hier()
        x_now = _from_params(self.runtime.fusion_threshold,
                             self.runtime.cycle_time_ms, ar_now, ag_now)
        self._opt.observe(x_now, score)
        if self._samples >= self.max_samples + self.warmup:
            fusion, cycle, hier_ar, hier_ag = _to_params(self._opt.best())
            self._submit(fusion, cycle, hier_ar, hier_ag, final=True)
            self._final_submitted = True
            LOG.info("autotune converged: fusion=%d cycle=%.2fms "
                     "hier_ar=%s hier_ag=%s", fusion, cycle, hier_ar,
                     hier_ag)
            return
        fusion, cycle, hier_ar, hier_ag = _to_params(self._opt.suggest())
        self._submit(fusion, cycle, hier_ar, hier_ag, final=False)
