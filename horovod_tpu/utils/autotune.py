"""Autotuner for eager-runtime parameters — synchronized Bayesian search.

Reference: /root/reference/horovod/common/parameter_manager.{h,cc} +
common/optim/bayesian_optimization.cc + gaussian_process.cc — Bayesian
optimization (Gaussian process + expected improvement) over
fusion-threshold and cycle-time, scored in bytes/sec, with the winning
parameters broadcast from the coordinator so every rank always runs the
same knobs (Controller::SynchronizeParameters, controller.cc:39-53 —
per-rank divergence would change fused-program signatures across ranks).

On TPU the compiled path needs no tuning (XLA schedules); the search space
is the *eager* runtime's fusion threshold and cycle time. Design:

- Rank 0 owns the GP: it scores its own smoothed bytes/sec (symmetric in
  data-parallel steady state), observes (params, score) pairs, and proposes
  the next point by maximizing expected improvement over log-scaled bounds.
- Every proposal is published to the rendezvous KV store (scope
  ``autotune``, key ``latest``); other ranks poll it cheaply each sample
  and apply any newer proposal. After ``max_samples`` the best observed
  point is published as final and tuning stops everywhere.
- Single-process (no controller): same GP, applied locally.

The GP here is an original small implementation: RBF kernel, fixed noise,
Cholesky solve, EI acquisition maximized over a quasi-random candidate set
(the role of the reference's L-BFGS ascent on the acquisition).
"""

from __future__ import annotations

import json
import logging
import math
import time
from typing import Optional

import numpy as np

LOG = logging.getLogger("horovod_tpu")

# log2-space bounds: fusion 1 MiB .. 256 MiB, cycle 0.5 .. 25 ms
_BOUNDS = np.array([[20.0, 28.0],
                    [math.log2(0.5), math.log2(25.0)]])


class _GP:
    """Minimal RBF-kernel Gaussian process (reference gaussian_process.cc
    role), inputs normalized to [0,1]^d."""

    def __init__(self, length_scale: float = 0.25, noise: float = 1e-3):
        self.ls = length_scale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._alpha = None
        self._L = None

    def _k(self, A, B):
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray):
        self._X = X
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))

    def predict(self, Xs: np.ndarray):
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


def _expected_improvement(mu, sigma, best, xi: float = 0.01):
    """EI acquisition (reference bayesian_optimization.cc:ExpectedImprovement
    semantics, original formula implementation)."""
    z = (mu - best - xi) / sigma
    # standard normal pdf/cdf without scipy
    pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    return (mu - best - xi) * cdf + sigma * pdf


class BayesianOptimizer:
    """Propose points in normalized [0,1]^d maximizing EI; first
    ``n_random`` proposals are low-discrepancy random exploration."""

    def __init__(self, dims: int = 2, n_random: int = 4, seed: int = 0):
        self.dims = dims
        self.n_random = n_random
        self.rng = np.random.RandomState(seed)
        self.X: list[np.ndarray] = []
        self.y: list[float] = []

    def observe(self, x: np.ndarray, score: float):
        self.X.append(np.asarray(x, float))
        self.y.append(float(score))

    def suggest(self) -> np.ndarray:
        if len(self.X) < self.n_random:
            return self.rng.uniform(size=self.dims)
        X = np.stack(self.X)
        y = np.asarray(self.y)
        scale = y.std() or 1.0
        gp = _GP()
        gp.fit(X, (y - y.mean()) / scale)
        cand = self.rng.uniform(size=(256, self.dims))
        mu, sigma = gp.predict(cand)
        ei = _expected_improvement(mu, sigma, (y.max() - y.mean()) / scale)
        return cand[int(np.argmax(ei))]

    def best(self) -> Optional[np.ndarray]:
        if not self.X:
            return None
        return self.X[int(np.argmax(self.y))]


def _to_params(x01: np.ndarray) -> tuple[int, float]:
    lo, hi = _BOUNDS[:, 0], _BOUNDS[:, 1]
    logs = lo + np.clip(x01, 0, 1) * (hi - lo)
    return int(2.0 ** logs[0]), float(2.0 ** logs[1])


def _from_params(fusion: int, cycle: float) -> np.ndarray:
    lo, hi = _BOUNDS[:, 0], _BOUNDS[:, 1]
    logs = np.array([math.log2(max(fusion, 1)), math.log2(max(cycle, 1e-3))])
    return np.clip((logs - lo) / (hi - lo), 0, 1)


class Autotuner:
    """Scores smoothed bytes/sec and drives the synchronized search.

    ``sample()`` is called from the background cycle loop every N working
    cycles on every rank; only rank 0 (or a controller-less single process)
    updates the GP and proposes; other ranks poll + apply.
    """

    SCOPE = "autotune"
    KEY = "latest"

    def __init__(self, runtime, log_path: str = "", warmup_samples: int = 3,
                 max_samples: int = 20):
        self.runtime = runtime
        self.log_path = log_path
        self.warmup = warmup_samples
        self.max_samples = max_samples
        self._samples = 0
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self._seq_applied = -1
        self.done = False
        ctl = runtime.controller
        self._client = ctl.client if ctl is not None else None
        self._rank = ctl.rank if ctl is not None else 0
        self._opt = BayesianOptimizer() if self._rank == 0 else None
        if log_path:
            with open(log_path, "w") as f:
                f.write("sample,fusion_bytes,cycle_ms,score_bytes_per_sec\n")

    # -- scoring ------------------------------------------------------------
    def _score(self) -> Optional[float]:
        now = time.monotonic()
        dt = now - self._last_time
        if dt <= 0:
            return None
        db = self.runtime.bytes_processed - self._last_bytes
        self._last_bytes = self.runtime.bytes_processed
        self._last_time = now
        return db / dt

    def _log(self, score: float):
        if self.log_path:
            with open(self.log_path, "a") as f:
                f.write(f"{self._samples},{self.runtime.fusion_threshold},"
                        f"{self.runtime.cycle_time_ms},{score:.1f}\n")

    # -- parameter broadcast (SynchronizeParameters, controller.cc:39-53) ---
    def _publish(self, fusion: int, cycle: float, final: bool):
        self._seq_applied += 1
        payload = json.dumps({"seq": self._seq_applied, "fusion": fusion,
                              "cycle": cycle, "final": final}).encode()
        if self._client is not None:
            try:
                self._client.put(self.SCOPE, self.KEY, payload)
            except Exception as e:
                LOG.warning("autotune publish failed: %s", e)

    def poll_params(self) -> bool:
        """Non-root: apply the coordinator's latest proposal if newer.
        Returns True when an update was applied. Public so tests and
        framework loops can force a final sync."""
        if self._client is None or self._rank == 0:
            return False
        try:
            raw = self._client.get(self.SCOPE, self.KEY, timeout=0.05)
        except Exception:
            return False
        msg = json.loads(raw)
        if msg["seq"] <= self._seq_applied:
            return False
        self._seq_applied = msg["seq"]
        self.runtime.fusion_threshold = int(msg["fusion"])
        self.runtime.cycle_time_ms = float(msg["cycle"])
        if msg.get("final"):
            self.done = True
        return True

    # -- main entry ---------------------------------------------------------
    def sample(self):
        if self._rank != 0:
            self.poll_params()
            score = self._score()
            if score is not None:
                self._samples += 1
                self._log(score)
            return
        if self.done:
            return
        score = self._score()
        if score is None:
            return
        self._samples += 1
        self._log(score)
        if self._samples <= self.warmup:
            return
        x_now = _from_params(self.runtime.fusion_threshold,
                             self.runtime.cycle_time_ms)
        self._opt.observe(x_now, score)
        if self._samples >= self.max_samples + self.warmup:
            fusion, cycle = _to_params(self._opt.best())
            self.runtime.fusion_threshold = fusion
            self.runtime.cycle_time_ms = cycle
            self._publish(fusion, cycle, final=True)
            self.done = True
            LOG.info("autotune converged: fusion=%d cycle=%.2fms",
                     fusion, cycle)
            return
        fusion, cycle = _to_params(self._opt.suggest())
        self.runtime.fusion_threshold = fusion
        self.runtime.cycle_time_ms = cycle
        self._publish(fusion, cycle, final=False)
