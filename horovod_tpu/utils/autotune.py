"""Joint online autotuner for the eager fast path — synchronized search.

Reference: /root/reference/horovod/common/parameter_manager.{h,cc} +
common/optim/bayesian_optimization.cc + gaussian_process.cc — Bayesian
optimization (Gaussian process + expected improvement) over the runtime
knobs, with the winning parameters broadcast from the coordinator so
every rank always runs the same knobs
(Controller::SynchronizeParameters, controller.cc:39-53 — per-rank
divergence would change fused-program signatures across ranks).

"Joint OP and Tensor Fusion" (arXiv:2209.12769) shows the wins come from
tuning the fast-path knobs *together*, so the search space here is the
whole configuration the steady state depends on:

- ``fusion``      — fusion threshold bytes (log2-continuous, 1..256 MiB)
- ``cycle``       — background cycle time ms (log2-continuous, 0.5..25)
- ``hier_ar/ag``  — hierarchical allreduce/allgather flags (categorical,
  relaxed to one thresholded dim each, as the reference does)
- ``ring_slots``  — staging-ring depth (categorical; FusionBuffer.set_slots)
- ``chunk``       — max tensors per fused chunk (categorical;
  HOROVOD_PLAN_CHUNK_TENSORS semantics, 0 = byte-bounded only)
- ``compression`` — wire mode none|bf16|int8|int4 (categorical; honors the
  PR-8 eligibility guardrails per tensor and the sharded-update mutual
  exclusion — the knob only exists when compression is legal at all)
- ``hier_group``  — hierarchical negotiation group size (categorical;
  KVController.set_group_size re-handshakes the channels)

Categorical knobs are one-hot blocks in the normalized vector; the GP
sees only *snapped* encodings (pure one-hots), and a UCB bandit over
one-knob-at-a-time arms drives the small-sample exploration phase where
a GP posterior is meaningless. Scoring prefers the perfledger goodput
signal (effective allreduce bytes/sec discounted by the exposed-comm
fraction, PerfLedger.window_score) and falls back to smoothed bytes/sec
when the ledger is off.

Safety: proposals ride the negotiated RESPONSE (KVController.submit_params
→ runtime._apply_tuned_params): every rank — rank 0 included — applies
them at response receipt, the same round boundary everywhere, with
all-or-nothing validation before any knob moves. Every boundary-moving
knob routes through its setter (plan invalidation / ring resize / channel
re-handshake). A candidate that regresses the score by
``HOROVOD_AUTOTUNE_REVERT_PCT`` percent for ``HOROVOD_AUTOTUNE_REVERT_WINDOWS``
consecutive windows is reverted to the best known config and penalized in
the optimizer. A workload shift (stable change in the per-cycle signature
of tensor names/shapes) restarts the search; the winning config persists
to ``HOROVOD_AUTOTUNE_TUNED_FILE`` with all-or-nothing parse on reload.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
import zlib
from typing import Optional

import numpy as np

from . import faults as faults_mod
from . import flightrec as flightrec_mod
from . import lockcheck
from . import metrics as metrics_mod

LOG = logging.getLogger("horovod_tpu")

# log2-space bounds kept for the legacy 4-dim layout (fusion 1..256 MiB,
# cycle 0.5..25 ms); the knob objects below are the canonical source
_BOUNDS = np.array([[20.0, 28.0],
                    [math.log2(0.5), math.log2(25.0)]])
_DIMS = 4

#: compression mode -> the bits value the hvd_autotune_compression_bits
#: gauge publishes (0 = uncompressed wire)
_COMP_BITS = {"none": 0, "bf16": 16, "int8": 8, "int4": 4}

#: consecutive sample windows a NEW dominant workload signature must
#: persist before the search restarts — debounces runs whose tensor
#: names legitimately vary cycle-to-cycle
SHIFT_WINDOWS = 3

TUNED_FILE_VERSION = 1


# ===========================================================================
# Search space: mixed continuous / categorical knobs over [0,1]^d
# ===========================================================================

class Knob:
    """One tuned parameter: a named slice of the normalized vector."""

    dims = 1

    def __init__(self, name: str):
        self.name = name


class LogKnob(Knob):
    """Continuous knob searched in log2 space (1 dim)."""

    def __init__(self, name: str, lo: float, hi: float,
                 integer: bool = False):
        super().__init__(name)
        self.lo = math.log2(lo)
        self.hi = math.log2(hi)
        self.integer = integer

    def decode(self, seg):
        t = min(max(float(seg[0]), 0.0), 1.0)
        v = 2.0 ** (self.lo + t * (self.hi - self.lo))
        return int(round(v)) if self.integer else float(v)

    def encode(self, value):
        v = math.log2(max(float(value), 1e-9))
        return [min(max((v - self.lo) / (self.hi - self.lo), 0.0), 1.0)]


class BoolKnob(Knob):
    """Binary knob relaxed to one thresholded dim (the reference's
    categorical handling for the hierarchical flags)."""

    def decode(self, seg):
        return bool(float(seg[0]) >= 0.5)

    def encode(self, value):
        return [0.75 if value else 0.25]


class ChoiceKnob(Knob):
    """Categorical knob as a one-hot block (argmax decode)."""

    def __init__(self, name: str, choices):
        super().__init__(name)
        self.choices = tuple(choices)
        self.dims = len(self.choices)

    def decode(self, seg):
        return self.choices[int(np.argmax(np.asarray(seg, float)))]

    def encode(self, value):
        seg = [0.0] * self.dims
        if value in self.choices:
            idx = self.choices.index(value)
        elif isinstance(value, (int, float)):
            # out-of-menu runtime value (e.g. a hand-set env knob): snap
            # to the nearest choice rather than failing the sample loop
            idx = int(np.argmin([abs(float(c) - float(value))
                                 for c in self.choices]))
        else:
            raise ValueError(f"{self.name}: {value!r} not in {self.choices}")
        seg[idx] = 1.0
        return seg


class SearchSpace:
    """Ordered knob set <-> normalized vector in [0,1]^dims."""

    def __init__(self, knobs):
        self.knobs = tuple(knobs)
        self.offsets = {}
        off = 0
        for k in self.knobs:
            self.offsets[k.name] = off
            off += k.dims
        self.dims = off

    def to_params(self, x) -> dict:
        x = np.asarray(x, float)
        out = {}
        for k in self.knobs:
            off = self.offsets[k.name]
            out[k.name] = k.decode(x[off:off + k.dims])
        return out

    def from_params(self, params: dict) -> np.ndarray:
        segs = []
        for k in self.knobs:
            segs.extend(k.encode(params[k.name]))
        return np.asarray(segs, float)

    def snap(self, x) -> np.ndarray:
        """Clip to [0,1] and collapse every one-hot block to a pure
        one-hot — the only encodings the GP is ever fit on or queried at,
        so categorical blocks stay on the feasible manifold."""
        x = np.clip(np.asarray(x, float), 0.0, 1.0)
        for k in self.knobs:
            if isinstance(k, ChoiceKnob):
                off = self.offsets[k.name]
                block = x[off:off + k.dims]
                hot = int(np.argmax(block))
                block[:] = 0.0
                block[hot] = 1.0
        return x

    def snap_rows(self, rows) -> np.ndarray:
        return np.stack([self.snap(r) for r in np.asarray(rows, float)])

    def arms(self):
        """The bandit's one-knob-at-a-time arms: every (knob, choice)
        over the categorical/boolean knobs."""
        out = []
        for k in self.knobs:
            if isinstance(k, ChoiceKnob):
                out.extend((k.name, i) for i in range(k.dims))
            elif isinstance(k, BoolKnob):
                out.extend((k.name, i) for i in (0, 1))
        return out

    def set_arm(self, x, arm):
        name, i = arm
        off = self.offsets[name]
        for k in self.knobs:
            if k.name == name:
                if isinstance(k, ChoiceKnob):
                    x[off:off + k.dims] = 0.0
                    x[off + i] = 1.0
                else:
                    x[off] = 0.75 if i else 0.25
                return
        raise KeyError(name)

    def continuous_offsets(self):
        return [self.offsets[k.name] for k in self.knobs
                if isinstance(k, LogKnob)]


def default_space() -> SearchSpace:
    """The legacy 4-dim layout: fusion, cycle, hier flags."""
    return SearchSpace([
        LogKnob("fusion", 1 << 20, 256 << 20, integer=True),
        LogKnob("cycle", 0.5, 25.0),
        BoolKnob("hier_ar"),
        BoolKnob("hier_ag"),
    ])


def build_space(runtime, config=None) -> SearchSpace:
    """The joint space for one runtime — knobs appear only where they are
    applicable AND legal (duck-typed runtimes without the setters keep
    the legacy 4-dim space; compression requires a real multi-process
    wire, enabled plans, and no sharded-update mutual exclusion; the hier
    group size requires an actually-hierarchical controller)."""
    knobs = [
        LogKnob("fusion", 1 << 20, 256 << 20, integer=True),
        LogKnob("cycle", 0.5, 25.0),
    ]
    ps = getattr(runtime, "process_set", None)
    cross = int(getattr(ps, "cross_size", 1) or 1) if ps is not None else 1
    # hierarchical programs need a backend with real cross-process
    # collectives; the CPU backend cannot compile them ("Multiprocess
    # computations aren't implemented"), so on cpu+multi-process the hier
    # knobs are pinned off instead of letting the search propose configs
    # whose every fused chunk can only fail
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    if cross <= 1 or backend != "cpu":
        knobs.append(BoolKnob("hier_ar"))
        knobs.append(BoolKnob("hier_ag"))
    if hasattr(runtime, "set_staging_slots"):
        knobs.append(ChoiceKnob("ring_slots", (1, 2, 4, 8)))
    if hasattr(runtime, "set_plan_chunk_tensors"):
        knobs.append(ChoiceKnob("chunk", (0, 2, 4, 8, 16)))
    if (hasattr(runtime, "set_compression_spec") and cross > 1
            and getattr(runtime, "_plans_enabled", False)
            and not getattr(runtime, "_sharded_update", False)):
        knobs.append(ChoiceKnob("compression",
                                ("none", "bf16", "int8", "int4")))
    ctl = getattr(runtime, "controller", None)
    if (ctl is not None and getattr(ctl, "_hier", False)
            and hasattr(ctl, "set_group_size")):
        size = int(getattr(ctl, "size", 2))
        choices = tuple(sorted({min(k, size) for k in (2, 4, 8, 16, 32)}))
        knobs.append(ChoiceKnob("hier_group", choices))
    return SearchSpace(knobs)


def _to_params(x01, space: Optional[SearchSpace] = None) -> dict:
    """Normalized vector -> knob dict (legacy 4-dim layout by default)."""
    return (space or default_space()).to_params(x01)


def _from_params(params: dict, space: Optional[SearchSpace] = None) -> np.ndarray:
    """Knob dict -> normalized vector; exact inverse of ``_to_params``
    for every decodable value (the round-trip the unit tests pin)."""
    return (space or default_space()).from_params(params)


# ===========================================================================
# Surrogate + acquisition
# ===========================================================================

class _GP:
    """Minimal RBF-kernel Gaussian process (reference gaussian_process.cc
    role), inputs normalized to [0,1]^d. ``fit`` retries the Cholesky with
    escalating jitter — duplicate observations (a penalized candidate is
    re-observed at its own x) make the plain kernel matrix singular."""

    def __init__(self, length_scale: float = 0.25, noise: float = 1e-3):
        self.ls = length_scale
        self.noise = noise
        self._X: Optional[np.ndarray] = None
        self._alpha = None
        self._L = None

    def _k(self, A, B):
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray):
        self._X = X
        K = self._k(X, X)
        noise = self.noise
        err = None
        for _ in range(8):
            try:
                self._L = np.linalg.cholesky(K + noise * np.eye(len(X)))
                err = None
                break
            except np.linalg.LinAlgError as e:
                err = e
                noise *= 10.0
        if err is not None:
            raise err
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))

    def predict(self, Xs: np.ndarray):
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


def _expected_improvement(mu, sigma, best, xi: float = 0.01):
    """EI acquisition (reference bayesian_optimization.cc:ExpectedImprovement
    semantics, original formula implementation)."""
    z = (mu - best - xi) / sigma
    # standard normal pdf/cdf without scipy
    pdf = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
    return (mu - best - xi) * cdf + sigma * pdf


def _argmax_tiebreak(ei, mu) -> int:
    """Deterministic acquisition argmax: EI ties (common when the
    surrogate is flat — every candidate far from data has the same EI)
    break on the posterior mean, then on index."""
    ei = np.round(np.asarray(ei, float), 12)
    top = np.flatnonzero(ei == ei.max())
    if len(top) == 1:
        return int(top[0])
    return int(top[int(np.argmax(np.asarray(mu, float)[top]))])


class BayesianOptimizer:
    """Propose points in normalized [0,1]^d maximizing EI. With a
    ``space``, categorical blocks are snapped to feasible one-hots and
    the first ``n_random`` proposals come from a UCB bandit over
    one-knob-at-a-time arms around the incumbent (the small-sample phase
    where a GP posterior is meaningless); without one, the legacy
    uniform-exploration behavior is preserved. Fully deterministic for a
    fixed seed and observation sequence."""

    def __init__(self, dims: int = 2, n_random: int = 4, seed: int = 0,
                 space: Optional[SearchSpace] = None):
        self.dims = dims
        self.n_random = n_random
        self.rng = np.random.RandomState(seed)
        self.space = space
        self.X: list[np.ndarray] = []
        self.y: list[float] = []
        self._arms = space.arms() if space is not None else []
        self._arm_n: dict = {}
        self._arm_sum: dict = {}
        self._last_arm = None

    def observe(self, x: np.ndarray, score: float):
        self.X.append(np.asarray(x, float))
        self.y.append(float(score))
        if self._last_arm is not None:
            a, self._last_arm = self._last_arm, None
            self._arm_n[a] = self._arm_n.get(a, 0) + 1
            self._arm_sum[a] = self._arm_sum.get(a, 0.0) + float(score)

    def penalize(self, x: np.ndarray):
        """Record ``x`` below the worst observation — the revert
        guardrail's memory: neither ``best()`` nor the surrogate will
        revisit a reverted candidate."""
        if not self.y:
            return
        worst = min(self.y)
        spread = (max(self.y) - worst) or abs(worst) or 1.0
        self.observe(np.asarray(x, float), worst - spread)

    def _explore(self) -> np.ndarray:
        if self._arms:
            inc = self.best()
            if inc is None:
                inc = np.full(self.dims, 0.5)
            x = np.array(inc, float, copy=True)
            # jitter the continuous dims around the incumbent so the
            # bandit rounds still gather curvature for the GP phase
            for off in self.space.continuous_offsets():
                x[off] = min(1.0, max(
                    0.0, x[off] + self.rng.uniform(-0.15, 0.15)))
            spread = ((max(self.y) - min(self.y)) if len(self.y) >= 2
                      else 0.0) or 1.0
            total = sum(self._arm_n.values()) + 1
            pick, pick_u = None, None
            for arm in self._arms:  # fixed order -> deterministic ties
                n = self._arm_n.get(arm, 0)
                if n == 0:
                    pick = arm
                    break
                u = (self._arm_sum[arm] / n
                     + spread * math.sqrt(2.0 * math.log(total) / n))
                if pick_u is None or u > pick_u:
                    pick, pick_u = arm, u
            self.space.set_arm(x, pick)
            self._last_arm = pick
            return self.space.snap(x)
        return self.rng.uniform(size=self.dims)

    def suggest(self) -> np.ndarray:
        if len(self.X) < self.n_random:
            return self._explore()
        X = np.stack(self.X)
        y = np.asarray(self.y)
        scale = y.std() or 1.0
        gp = _GP()
        gp.fit(X, (y - y.mean()) / scale)
        cand = self.rng.uniform(size=(256, self.dims))
        inc = self.best()
        if inc is not None:
            # local refinement pool around the incumbent: EI over pure
            # uniform candidates alone under-samples the basin the best
            # point sits in once dims grow past a handful
            local = np.clip(
                inc + self.rng.normal(scale=0.08, size=(64, self.dims)),
                0.0, 1.0)
            cand = np.vstack([cand, local])
        if self.space is not None:
            cand = self.space.snap_rows(cand)
        mu, sigma = gp.predict(cand)
        ei = _expected_improvement(mu, sigma, (y.max() - y.mean()) / scale)
        return cand[_argmax_tiebreak(ei, mu)]

    def best(self) -> Optional[np.ndarray]:
        if not self.X:
            return None
        return self.X[int(np.argmax(self.y))]


# ===========================================================================
# Tuned-file persistence (all-or-nothing)
# ===========================================================================

#: knob name -> validator for tuned-file reload; a file containing any
#: unknown key or failing any validator is rejected WHOLE (no partial
#: configs ever reach the runtime)
_PARAM_CHECKS = {
    "fusion": lambda v: isinstance(v, int) and v > 0,
    "cycle": lambda v: isinstance(v, (int, float)) and v > 0,
    "hier_ar": lambda v: isinstance(v, bool),
    "hier_ag": lambda v: isinstance(v, bool),
    "ring_slots": lambda v: isinstance(v, int) and v >= 1,
    "chunk": lambda v: isinstance(v, int) and v >= 0,
    "compression": lambda v: v in ("none", "bf16", "int8", "int4"),
    "hier_group": lambda v: isinstance(v, int) and v >= 1,
}


def save_tuned_config(path: str, params: dict, score: float) -> None:
    """Atomically persist the winning config (tmp + os.replace, so a kill
    mid-write can never leave a truncated file for reload to choke on)."""
    doc = {"version": TUNED_FILE_VERSION,
           "params": dict(params), "score": float(score)}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def load_tuned_config(path: str) -> Optional[dict]:
    """All-or-nothing reload: the params dict, or None if the file is
    missing, unparseable, the wrong version, or ANY key/value fails
    validation — a half-good file must not half-apply."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except Exception as e:
        LOG.warning("autotune tuned file %s unreadable: %s", path, e)
        return None
    if not isinstance(doc, dict) or doc.get("version") != TUNED_FILE_VERSION:
        LOG.warning("autotune tuned file %s: unsupported layout", path)
        return None
    params = doc.get("params")
    if not isinstance(params, dict) or not params:
        LOG.warning("autotune tuned file %s: missing params", path)
        return None
    for k, v in params.items():
        check = _PARAM_CHECKS.get(k)
        if check is None or not check(v):
            LOG.warning("autotune tuned file %s: bad entry %s=%r "
                        "(rejecting whole file)", path, k, v)
            return None
    return params


# ===========================================================================
# The autotuner
# ===========================================================================

class Autotuner:
    """Scores goodput windows and drives the synchronized joint search.

    ``sample()`` is called from the background cycle loop every N working
    cycles on every rank; only rank 0 (or a controller-less single
    process) updates the optimizer and proposes; other ranks apply
    proposals as they arrive on negotiated responses. ``note_cycle()``
    accumulates the per-cycle workload signature feeding shift detection.
    """

    def __init__(self, runtime, log_path: str = "", warmup_samples: int = 3,
                 max_samples: int = 20, config=None, tuned_file: str = None,
                 revert_pct: float = None, revert_windows: int = None,
                 seed: int = 0):
        self.runtime = runtime
        self.log_path = log_path
        self.warmup = warmup_samples
        self.max_samples = max_samples
        self.tuned_file = (tuned_file if tuned_file is not None
                           else getattr(config, "autotune_tuned_file", ""))
        self.revert_pct = float(
            revert_pct if revert_pct is not None
            else getattr(config, "autotune_revert_pct", 20.0))
        self.revert_windows = max(1, int(
            revert_windows if revert_windows is not None
            else getattr(config, "autotune_revert_windows", 2)))
        self._seed = int(seed)
        self._samples = 0
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self._led_cursor = 0
        self.done = False
        self._final_submitted = False
        self._best_score: Optional[float] = None
        self._best_params: Optional[dict] = None
        self._strikes = 0
        # workload-shift detection state (note_cycle runs on the cycle
        # thread, metric readers elsewhere — the counts dict is the only
        # cross-thread shared state)
        self._lock = lockcheck.make_lock("autotune.state")
        self._sig_counts: dict = {}  # guarded-by: _lock
        self._active_sig: Optional[int] = None
        self._shift_sig: Optional[int] = None
        self._shift_seen = 0
        ctl = runtime.controller
        self._rank = ctl.rank if ctl is not None else 0
        self.space = build_space(runtime, config)
        self._opt = (self._new_opt() if self._rank == 0 else None)
        self._warm_params: Optional[dict] = None
        if self._rank == 0 and self.tuned_file:
            self._warm_params = load_tuned_config(self.tuned_file)
            if self._warm_params is not None:
                # drop knobs this runtime's space doesn't carry (e.g. a
                # file tuned with hierarchy on, reloaded without it)
                names = {k.name for k in self.space.knobs}
                self._warm_params = {k: v for k, v in
                                     self._warm_params.items() if k in names}
        reg = metrics_mod.get_registry()
        self._m_fusion = reg.gauge("hvd_autotune_fusion_threshold_bytes",
                                   "currently applied fusion threshold")
        self._m_cycle = reg.gauge("hvd_autotune_cycle_time_ms",
                                  "currently applied cycle time")
        self._m_score = reg.gauge("hvd_autotune_last_score_bytes_per_sec",
                                  "last goodput score sample")
        self._m_samples = reg.counter("hvd_autotune_samples_total",
                                      "autotune score samples taken")
        self._m_done = reg.gauge("hvd_autotune_converged",
                                 "1 once the search has converged")
        self._m_rounds = reg.counter("hvd_autotune_rounds_total",
                                     "candidate configs proposed")
        self._m_best = reg.gauge("hvd_autotune_best_score",
                                 "best goodput score observed")
        self._m_reverts = reg.counter(
            "hvd_autotune_reverts_total",
            "regressing candidates reverted by the guardrail")
        self._m_shifts = reg.counter(
            "hvd_autotune_workload_shifts_total",
            "workload-signature shifts that restarted the search")
        self._m_ring = reg.gauge("hvd_autotune_ring_slots",
                                 "currently applied staging-ring slots")
        self._m_chunk = reg.gauge("hvd_autotune_plan_chunk_tensors",
                                  "currently applied per-chunk tensor cap")
        self._m_comp = reg.gauge("hvd_autotune_compression_bits",
                                 "active wire compression width (0=none)")
        self._m_group = reg.gauge("hvd_autotune_hier_group_size",
                                  "currently applied hier group size")
        if log_path:
            with open(log_path, "w") as f:
                f.write("sample,fusion_bytes,cycle_ms,hier_allreduce,"
                        "hier_allgather,ring_slots,chunk_tensors,"
                        "compression,hier_group,score\n")

    def _new_opt(self) -> BayesianOptimizer:
        return BayesianOptimizer(dims=self.space.dims, n_random=4,
                                 seed=self._seed, space=self.space)

    # -- scoring ------------------------------------------------------------
    def _score(self) -> Optional[float]:
        led = getattr(self.runtime, "ledger", None)
        if led is not None:
            self._led_cursor, score, _ = led.window_score(self._led_cursor)
            return score
        now = time.monotonic()
        dt = now - self._last_time
        if dt <= 0:
            return None
        db = self.runtime.bytes_processed - self._last_bytes
        self._last_bytes = self.runtime.bytes_processed
        self._last_time = now
        return db / dt

    @staticmethod
    def _get_hier() -> tuple[bool, bool]:
        from horovod_tpu.common import context as ctx_mod

        cfg = ctx_mod.context().config
        return cfg.hierarchical_allreduce, cfg.hierarchical_allgather

    @staticmethod
    def _set_hier(hier_ar: bool, hier_ag: bool):
        from horovod_tpu.common import context as ctx_mod
        from horovod_tpu.ops import megaplan as megaplan_mod

        cfg = ctx_mod.context().config
        cfg.hierarchical_allreduce = bool(hier_ar)
        cfg.hierarchical_allgather = bool(hier_ag)
        # hier topology is a plan-key ingredient: a captured whole-step
        # schedule spanning the flip must not replay (the coordinator
        # path funnels in _apply_tuned_params; this direct path must too)
        megaplan_mod.invalidate_megaplan("hier_topology")

    def _current_params(self) -> dict:
        """The runtime's live knob values in this space's vocabulary —
        what ``bench.py`` reports as the active tuned config."""
        rt = self.runtime
        out = {}
        for k in self.space.knobs:
            n = k.name
            if n == "fusion":
                out[n] = int(rt.fusion_threshold)
            elif n == "cycle":
                out[n] = float(rt.cycle_time_ms)
            elif n == "hier_ar":
                out[n] = self._get_hier()[0]
            elif n == "hier_ag":
                out[n] = self._get_hier()[1]
            elif n == "ring_slots":
                out[n] = int(getattr(rt, "staging_ring_slots", 4))
            elif n == "chunk":
                out[n] = int(getattr(rt, "plan_chunk_tensors", 0))
            elif n == "compression":
                from ..ops import compression as compression_mod

                out[n] = compression_mod.mode_of_spec(
                    getattr(rt, "_quant", None))
            elif n == "hier_group":
                out[n] = int(rt.controller._group_size)
        return out

    def active_config(self) -> dict:
        return self._current_params()

    def _log(self, score: float):
        self._m_samples.inc()
        self._m_score.set(score)
        self._m_fusion.set(self.runtime.fusion_threshold)
        self._m_cycle.set(self.runtime.cycle_time_ms)
        self._m_done.set(1 if (self.done or self._final_submitted) else 0)
        if self._best_score is not None:
            self._m_best.set(self._best_score)
        p = self._current_params()
        self._m_ring.set(p.get("ring_slots", 0))
        self._m_chunk.set(p.get("chunk", 0))
        self._m_comp.set(_COMP_BITS.get(p.get("compression", "none"), 0))
        self._m_group.set(p.get("hier_group", 0))
        if self.log_path:
            ar, ag = self._get_hier()
            with open(self.log_path, "a") as f:
                f.write(f"{self._samples},{self.runtime.fusion_threshold},"
                        f"{self.runtime.cycle_time_ms},{int(ar)},{int(ag)},"
                        f"{p.get('ring_slots', '')},{p.get('chunk', '')},"
                        f"{p.get('compression', '')},"
                        f"{p.get('hier_group', '')},{score:.1f}\n")

    # -- workload-shift detection -------------------------------------------
    def note_cycle(self, batch):
        """Cheap per-working-cycle signature of the tensor names/shapes —
        called from the cycle loop only while tuning is on (the off state
        never reaches here; zero-cost contract)."""
        if not batch:
            return
        h = 0
        for e in batch:
            shape = tuple(getattr(e.tensor, "shape", ()) or ())
            # crc32, not hash(): stable across processes and restarts
            h ^= zlib.crc32(f"{e.name}:{shape}".encode())
        with self._lock:
            self._sig_counts[h] = self._sig_counts.get(h, 0) + 1

    def _window_sig(self) -> Optional[int]:
        """Dominant cycle signature of the window just ended (counts
        reset); deterministic tie-break on the signature value."""
        with self._lock:
            counts, self._sig_counts = self._sig_counts, {}
        if not counts:
            return None
        return max(sorted(counts), key=counts.get)

    def _check_shift(self, sig: Optional[int]):
        if sig is None:
            return
        if self._active_sig is None:
            self._active_sig = sig
            return
        if sig == self._active_sig:
            self._shift_sig = None
            self._shift_seen = 0
            return
        # new dominant signature: debounce — only a signature that stays
        # dominant for SHIFT_WINDOWS consecutive windows is a workload
        # shift (per-cycle name churn must not thrash the search)
        if sig == self._shift_sig:
            self._shift_seen += 1
        else:
            self._shift_sig = sig
            self._shift_seen = 1
        if self._shift_seen < SHIFT_WINDOWS:
            return
        self._active_sig = sig
        self._shift_sig = None
        self._shift_seen = 0
        self._m_shifts.inc()
        flightrec_mod.note("autotune_step", action="workload_shift",
                           sig=sig)
        if self._rank != 0:
            return
        LOG.info("autotune: workload shifted, restarting search")
        self._restart_search()

    def _restart_search(self):
        """Re-arm the search from scratch (rank 0 only): workload-shift
        and health-drift restarts share this path. Old scores measured a
        different workload, so they are voided — including _best_score /
        _best_params, so the revert guardrail cannot loop the search
        back onto a config tuned for the pre-shift regime."""
        self._samples = 0
        self.done = False
        self._final_submitted = False
        self._strikes = 0
        self._best_score = None
        self._best_params = None
        self._opt = self._new_opt()
        self._m_done.set(0)

    def note_health_drift(self, series: str):
        """A latched health drift verdict (utils/health.py) on a goodput
        series the tuner optimizes — treat it as a confirmed workload
        shift and restart the search. Debounce lives on the health side:
        anomalies latch once per episode, so one drifted regime provokes
        at most one re-tune until the series clears and re-arms."""
        self._m_shifts.inc()
        flightrec_mod.note("autotune_step", action="health_drift",
                           series=series)
        if self._rank != 0:
            return
        LOG.info("autotune: health drift on %r, restarting search", series)
        self._restart_search()

    # -- parameter broadcast (SynchronizeParameters, controller.cc:39-53) ---
    def _submit(self, params: dict, final: bool):
        """Hand the proposal to the coordinator: it rides the next
        negotiated response and applies on EVERY rank (this one included)
        at response receipt — never asynchronously, because a per-rank
        divergence in the program-shaping knobs (hier flags/group,
        compression) would build different XLA programs for the same
        negotiated tensor and corrupt the wire."""
        p = dict(params)
        p["final"] = bool(final)
        ctl = self.runtime.controller
        if ctl is not None:
            ctl.submit_params(p)
            return
        apply = getattr(self.runtime, "_apply_tuned_params", None)
        ps = getattr(self.runtime, "process_set", None)
        multi = ps is not None and getattr(ps, "cross_size", 1) > 1
        if apply is not None:
            if multi:
                # multi-process WITHOUT a rendezvous store (name-ordered
                # fallback): fusion/cycle may tune per-rank (no cross-rank
                # fusion on this path), but the program-shaping knobs MUST
                # NOT diverge, so they never apply here
                p = {k: p[k] for k in ("fusion", "cycle", "final")
                     if k in p}
            apply(p)
            if final:
                self.done = True
            return
        # duck-typed runtime without the apply hook (kept working for
        # embedding tests/harnesses): direct attribute application
        setter = getattr(self.runtime, "set_fusion_threshold", None)
        if setter is not None:
            setter(int(p["fusion"]))
        else:
            from horovod_tpu.ops import collectives as collectives_mod

            self.runtime.fusion_threshold = int(p["fusion"])
            # the real setter invalidates cached fused plans itself; the
            # duck-typed direct write must reach the same funnel or a
            # stale plan keyed on the old threshold keeps executing
            collectives_mod.invalidate_fused_plans()
        self.runtime.cycle_time_ms = float(p["cycle"])
        if not multi and ("hier_ar" in p or "hier_ag" in p):
            self._set_hier(p.get("hier_ar", False), p.get("hier_ag", False))
        if final:
            self.done = True

    def _propose(self, params: dict, final: bool):
        """One atomic proposal: the fault point fires BEFORE anything is
        handed over, so an injected fault skips the round whole — a torn
        (partially submitted) config cannot exist."""
        faults_mod.fault_point("autotune.propose")
        flightrec_mod.note("autotune_step",
                           action="converge" if final else "propose",
                           sample=self._samples)
        self._m_rounds.inc()
        self._submit(params, final)

    def _guardrail(self, score: float, params_now: dict,
                   x_now: Optional[np.ndarray]) -> bool:
        """Convergence guardrail: a candidate regressing the score by
        >= revert_pct percent for revert_windows consecutive windows is
        reverted to the best known config and penalized. Returns True
        when a revert was submitted this window."""
        if self._best_score is None or self._best_params is None:
            return False
        if params_now == self._best_params:
            self._strikes = 0
            return False
        if score >= self._best_score * (1.0 - self.revert_pct / 100.0):
            self._strikes = 0
            return False
        self._strikes += 1
        if self._strikes < self.revert_windows:
            return False
        self._strikes = 0
        if x_now is not None and self._opt is not None:
            self._opt.penalize(x_now)
        self._m_reverts.inc()
        flightrec_mod.note("autotune_step", action="revert",
                           sample=self._samples)
        LOG.info("autotune: candidate regressed >=%.0f%% for %d windows, "
                 "reverting to best config", self.revert_pct,
                 self.revert_windows)
        self._propose(self._best_params,
                      final=self.done or self._final_submitted)
        return True

    def _converge(self):
        x_best = self._opt.best()
        params = (self.space.to_params(x_best) if x_best is not None
                  else self._current_params())
        self._final_submitted = True
        self._propose(params, final=True)
        self._m_done.set(1)
        if self.tuned_file:
            try:
                save_tuned_config(self.tuned_file, params,
                                  self._best_score or 0.0)
            except Exception:
                LOG.exception("autotune tuned-file write failed")
        LOG.info("autotune converged: %s", params)

    # -- main entry ---------------------------------------------------------
    def sample(self):
        if self._rank != 0:
            # params arrive via the negotiated response
            # (runtime._apply_tuned_params); score for observability only
            self._check_shift(self._window_sig())
            score = self._score()
            if score is not None:
                self._samples += 1
                self._log(score)
            return
        if self._warm_params is not None:
            # persisted config (tuned file): first proposal, through the
            # same synchronized path as any candidate
            p, self._warm_params = self._warm_params, None
            self._propose(p, final=False)
            return
        self._check_shift(self._window_sig())
        score = self._score()
        if score is None:
            return
        self._samples += 1
        self._log(score)
        if self.done or self._final_submitted:
            # steady state: the guardrail keeps watching (a re-applied
            # stale config after elastic restore, say, must still revert)
            self._guardrail(score, self._current_params(), None)
            return
        if self._samples <= self.warmup:
            return
        params_now = self._current_params()
        x_now = self.space.from_params(params_now)
        self._opt.observe(x_now, score)
        if self._best_score is None or score > self._best_score:
            self._best_score = score
            self._best_params = params_now
            self._m_best.set(score)
        if self._guardrail(score, params_now, x_now):
            return
        if self._samples >= self.max_samples + self.warmup:
            self._converge()
            return
        self._propose(self.space.to_params(self._opt.suggest()),
                      final=False)
