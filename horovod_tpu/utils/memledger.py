"""Device-memory & compile ledger: HBM samples, plan-cost profiles,
OOM forensics.

The observability stack so far watches *time* (metrics → tracing →
flight recorder → perf ledger) but is blind to the two resources the
recent tentpoles actually trade in: device memory and XLA compile cost.
The ZeRO-1 sharded update (opt/sharded.py) claims a ~1/N optimizer-state
footprint and the quantized wire (ops/compression.py) claims smaller
buffers, yet neither claim was measured at runtime — exactly the gap
arXiv:2004.13336 motivates sharding with (per-replica memory is the
scaling wall). And on tunneled TPU platforms every compile is a flaky
RPC (utils/compile_cache.py), so compile latency and persistent-cache
efficacy are production signals, not curiosities.

This module is both ledgers:

- **Memory side**: per-device stats via jax ``memory_stats()`` with a
  graceful fallback to live-array byte sums on platforms without an
  allocator stats API (CPU), sampled on the MetricsDumper cadence plus
  event-driven samples at plan build, elastic resize, and sharded-layout
  (re)build. Each sample carries a per-component attribution (plan
  cache / staging ring / EF residuals / sharded optimizer state) so the
  1/N sharding claim is a measured number. Exposure: ``hvd_mem_*``
  series, a ``mem/rank{k}`` KV push merged by the launcher's
  ``GET /memory``, and ``hvd.memory_report()``.
- **Compile side**: every fused/sharded/quantized plan built by
  ops/collectives.py is wrapped (:func:`instrument_plan`) so its
  first-call XLA compile is timed ahead-of-time and its serialized
  program size recorded, keyed by plan kind (``hvd_compile_seconds``
  histogram, ``hvd_compile_program_bytes_total{kind}``).
  Persistent-cache hit/miss is inferred from the cache-dir entry delta
  across the compile (utils/compile_cache.py records the active dir).
  Compile stalls are fed into the perf ledger's host-overhead
  attribution (``PerfLedger.note_compile``) so a recompile storm shows
  in ``hvd.perf_report()`` and can be bounded by an ``HOROVOD_SLO_SPEC``
  budget (``compile_seconds_p95<=…``).
- **Forensics**: :func:`forensics` assembles the memory section of the
  diagnostics bundle (utils/diag.py) — last N ledger samples, top live
  buffers by size, component attribution, and the suspect (dominant)
  component — so a ``RESOURCE_EXHAUSTED`` crash yields a named suspect
  instead of a dead rank.

Zero-cost contract (same as utils/tracing.py / utils/perfledger.py,
enforced by hvdlint's zero-cost-hooks rule and
benchmarks/memledger_overhead.py): with ``HOROVOD_MEMLEDGER`` unset no
ledger exists, hook sites pay one ``is None`` check, and no
``hvd_mem_*``/``hvd_compile_*`` series is registered. Metric handles are
resolved in ``MemLedger.__init__`` — lazily at enable — so the off state
adds zero series. Plan instrumentation additionally arms when
``HOROVOD_PLAN_CACHE_MAX_BYTES`` caps the plan cache (the cap needs the
per-plan program sizes even without the ledger).
"""

from __future__ import annotations

import collections
import logging
import os
import time
from typing import Callable, List, Optional

from ..common import env as env_schema
from . import flightrec as flightrec_mod
from . import lockcheck

LOG = logging.getLogger("horovod_tpu")

#: KV scope the MetricsDumper pushes per-rank ledger snapshots under
#: (``mem/rank{k}``); the launcher's ``GET /memory`` merges the scope.
KV_SCOPE = "mem"

DEFAULT_CAPACITY = 512

#: How many compile records the compile ring keeps (compiles are rare —
#: a full ring means a recompile storm, which is exactly when the tail
#: matters).
COMPILE_RING = 256

#: The attributed memory components every sample carries. ``plan_cache``
#: / ``staging_ring`` / ``ef_residuals`` are pulled from their owners at
#: sample time; ``sharded_state`` is pushed by opt/sharded.py when the
#: sharded optimizer state is (re)built.
COMPONENTS = ("plan_cache", "staging_ring", "ef_residuals",
              "sharded_state")


def _device_memory() -> List[dict]:
    """Per-device allocator stats where the backend exposes them (TPU,
    GPU). Devices without ``memory_stats()`` (CPU) are simply absent —
    the caller falls back to live-array sums."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out.append({
            "device": f"{dev.platform}:{dev.id}",
            "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0) or 0),
            "bytes_limit": int(stats.get("bytes_limit", 0) or 0),
        })
    return out


def _live_array_bytes() -> int:
    """CPU fallback: total bytes held by live jax arrays in this
    process. Coarser than allocator stats (no limit, no allocator
    overhead) but honest about what the process retains."""
    try:
        import jax

        arrs = jax.live_arrays()
    except Exception:
        return 0
    total = 0
    for a in arrs:
        try:
            total += int(a.nbytes)
        except Exception:
            continue
    return total


def top_live_buffers(n: int = 10) -> List[dict]:
    """The ``n`` largest live jax arrays — the "what is actually holding
    memory" table of the OOM forensics section."""
    try:
        import jax

        arrs = jax.live_arrays()
    except Exception:
        return []
    infos = []
    for a in arrs:
        try:
            infos.append({"shape": list(a.shape), "dtype": str(a.dtype),
                          "nbytes": int(a.nbytes)})
        except Exception:
            continue
    infos.sort(key=lambda i: -i["nbytes"])
    return infos[:max(int(n), 0)]


def _program_bytes(compiled) -> int:
    """Serialized-program size of an AOT-compiled executable, best
    effort: the compiler's own generated-code figure, else the HLO text
    length as a proxy, else 0 (never raises)."""
    try:
        ma = compiled.memory_analysis()
        size = getattr(ma, "generated_code_size_in_bytes", None)
        if size:
            return int(size)
    except Exception:
        pass
    try:
        return len(compiled.as_text())
    except Exception:
        return 0


def _cache_dir_entries(path: str) -> int:
    try:
        return len(os.listdir(path))
    except OSError:
        return -1


class MemLedger:
    """Bounded ring of memory samples + compile-cost accounting.

    ``sample()`` runs on the MetricsDumper cadence plus rare events
    (plan build, reshard, elastic resize) — never per cycle — so it may
    walk live arrays and pull component owners. ``record_compile()``
    fires once per XLA compile. Both are safe from any thread.
    """

    def __init__(self, rank: int = 0, capacity: int = DEFAULT_CAPACITY):
        self.rank = rank
        self.capacity = max(int(capacity), 16)
        self._lock = lockcheck.make_lock("memledger.ring")
        self._ring = collections.deque(maxlen=self.capacity)  # guarded-by: _lock
        self._components: dict = {}  # guarded-by: _lock
        self._peak_live = 0  # guarded-by: _lock
        self._samples_total = 0  # guarded-by: _lock
        self._compiles = collections.deque(maxlen=COMPILE_RING)  # guarded-by: _lock
        self._compile_total_s = 0.0  # guarded-by: _lock
        self._compile_count = 0  # guarded-by: _lock
        self._compile_bytes = 0  # guarded-by: _lock
        from . import metrics as metrics_mod

        self._reg = metrics_mod.get_registry()
        self._m_live = self._reg.gauge(
            "hvd_mem_live_bytes",
            "live device/host-backed array bytes at the last sample")
        self._m_peak = self._reg.gauge(
            "hvd_mem_peak_bytes",
            "high-watermark of live bytes (allocator peak where the "
            "backend reports one, else max sampled live bytes)")
        self._m_comp = {
            comp: self._reg.gauge(
                "hvd_mem_component_bytes",
                "attributed bytes held by one runtime component",
                component=comp)
            for comp in COMPONENTS}
        # per-event sample counters and per-kind compile series are
        # label-lazy (events/kinds arrive at runtime); the base names are
        # fixed here so the docs/series contract stays literal
        self._m_samples: dict = {}
        self._m_compile_s: dict = {}
        self._m_compile_bytes: dict = {}
        self._m_persistent: dict = {}

    # -- memory side -------------------------------------------------------

    def _pull_components(self) -> dict:
        """Current attribution from the component owners; every pull is
        best-effort (a half-built runtime must not break a sample)."""
        comps = {}
        try:
            from ..ops import collectives as collectives_mod

            comps["plan_cache"] = int(collectives_mod.plan_cache_bytes())
        except Exception:
            pass
        try:
            from ..common import context as context_mod

            runtime = getattr(context_mod._ctx, "runtime", None)
        except Exception:
            runtime = None
        if runtime is not None:
            try:
                fb = getattr(runtime, "fusion_buffer", None)
                if fb is not None:
                    comps["staging_ring"] = int(fb.allocated_bytes())
            except Exception:
                pass
            try:
                store = getattr(runtime, "_quant_residuals", None)
                if store is not None:
                    comps["ef_residuals"] = int(store.nbytes())
            except Exception:
                pass
        return comps

    def sample(self, event: str = "interval") -> dict:
        """Take one memory sample and publish the ``hvd_mem_*`` series.

        ``event`` labels why the sample fired (``interval`` for the
        dumper cadence; ``plan_build`` / ``reshard`` /
        ``sharded_state_build`` / ``elastic_resize`` for the
        event-driven sites).
        """
        devices = _device_memory()
        live = sum(d["bytes_in_use"] for d in devices)
        dev_peak = sum(d["peak_bytes_in_use"] for d in devices)
        source = "memory_stats"
        if not devices:
            live = _live_array_bytes()
            source = "live_arrays"
        pulled = self._pull_components()
        with self._lock:
            self._components.update(pulled)
            comps = dict(self._components)
            self._peak_live = max(self._peak_live, live, dev_peak)
            peak = self._peak_live
            self._samples_total += 1
            snap = {"ts": time.time(), "ts_mono": time.monotonic(),
                    "event": event, "source": source,
                    "live_bytes": int(live), "peak_bytes": int(peak),
                    "devices": devices, "components": comps}
            self._ring.append(snap)
        self._m_live.set(int(live))
        self._m_peak.set(int(peak))
        for comp, nbytes in comps.items():
            gauge = self._m_comp.get(comp)
            if gauge is None:
                gauge = self._reg.gauge(
                    "hvd_mem_component_bytes",
                    "attributed bytes held by one runtime component",
                    component=comp)
                self._m_comp[comp] = gauge
            gauge.set(int(nbytes))
        counter = self._m_samples.get(event)
        if counter is None:
            counter = self._reg.counter(
                "hvd_mem_samples_total", "memory-ledger samples taken",
                event=event)
            self._m_samples[event] = counter
        counter.inc()
        return snap

    def set_component(self, component: str, nbytes: int) -> None:
        """Push-style attribution for owners that know their footprint
        at (re)build time rather than exposing an accessor
        (opt/sharded.py's sharded optimizer state)."""
        nbytes = int(nbytes)
        with self._lock:
            self._components[component] = nbytes
        gauge = self._m_comp.get(component)
        if gauge is None:
            gauge = self._reg.gauge(
                "hvd_mem_component_bytes",
                "attributed bytes held by one runtime component",
                component=component)
            self._m_comp[component] = gauge
        gauge.set(nbytes)

    def components(self) -> dict:
        with self._lock:
            return dict(self._components)

    def samples(self, last: Optional[int] = None) -> List[dict]:
        """The sample ring, oldest first (``last`` keeps the newest N)."""
        with self._lock:
            out = list(self._ring)
        if last is not None:
            out = out[-int(last):]
        return out

    # -- compile side ------------------------------------------------------

    def record_compile(self, kind: str, seconds: float,
                       program_bytes: int = 0,
                       persistent: Optional[str] = None) -> None:
        """Account one XLA compile: per-kind histogram + program-size
        counter, the compile ring, a ``compile`` flight-recorder event,
        the perf ledger's host-overhead attribution, and an event-driven
        memory sample (a compile IS a plan build)."""
        seconds = max(float(seconds), 0.0)
        program_bytes = max(int(program_bytes), 0)
        entry = {"ts": time.time(), "kind": kind,
                 "seconds": round(seconds, 6),
                 "program_bytes": program_bytes,
                 "persistent_cache": persistent}
        with self._lock:
            self._compiles.append(entry)
            self._compile_total_s += seconds
            self._compile_count += 1
            self._compile_bytes += program_bytes
        hist = self._m_compile_s.get(kind)
        if hist is None:
            from . import metrics as metrics_mod

            hist = self._reg.histogram(
                "hvd_compile_seconds", "XLA compile wall time per plan",
                buckets=metrics_mod.LATENCY_BUCKETS_S, kind=kind)
            self._m_compile_s[kind] = hist
        hist.observe(seconds)
        ctr = self._m_compile_bytes.get(kind)
        if ctr is None:
            ctr = self._reg.counter(
                "hvd_compile_program_bytes_total",
                "serialized XLA program bytes compiled, by plan kind",
                kind=kind)
            self._m_compile_bytes[kind] = ctr
        ctr.inc(program_bytes)
        if persistent is not None:
            pctr = self._m_persistent.get(persistent)
            if pctr is None:
                pctr = self._reg.counter(
                    "hvd_compile_persistent_cache_total",
                    "persistent compile-cache verdicts inferred from the "
                    "cache-dir entry delta across a compile",
                    verdict=persistent)
                self._m_persistent[persistent] = pctr
            pctr.inc()
        flightrec_mod.note("compile", kind=kind,
                           seconds=round(seconds, 4),
                           program_bytes=program_bytes,
                           persistent_cache=persistent, rank=self.rank)
        from . import perfledger as perfledger_mod

        pledger = perfledger_mod.get_ledger()
        if pledger is not None:
            pledger.note_compile(seconds)
        from . import anatomy as anatomy_mod

        profiler = anatomy_mod.get_profiler()
        if profiler is not None:
            profiler.note_compile(seconds)
        self.sample(event="plan_build")

    def compile_stats(self) -> dict:
        """Derived compile-cost view (also the source of the
        ``compile_seconds_*`` extras bench.py reports)."""
        with self._lock:
            entries = list(self._compiles)
            total_s = self._compile_total_s
            count = self._compile_count
            total_bytes = self._compile_bytes
        secs = sorted(e["seconds"] for e in entries)
        by_kind: dict = {}
        persistent = {"hit": 0, "miss": 0, "unknown": 0}
        for e in entries:
            k = by_kind.setdefault(e["kind"],
                                   {"compiles": 0, "seconds": 0.0,
                                    "program_bytes": 0})
            k["compiles"] += 1
            k["seconds"] = round(k["seconds"] + e["seconds"], 6)
            k["program_bytes"] += e["program_bytes"]
            verdict = e["persistent_cache"] or "unknown"
            persistent[verdict] = persistent.get(verdict, 0) + 1
        from .perfledger import _percentile

        return {"compiles": count,
                "compile_seconds_total": round(total_s, 6),
                "compile_seconds_p95": round(_percentile(secs, 0.95), 6),
                "compile_program_bytes_total": int(total_bytes),
                "persistent_cache": persistent,
                "by_kind": by_kind}

    # -- views -------------------------------------------------------------

    def suspect_component(self) -> Optional[str]:
        """The dominant attributed component — the OOM forensics
        verdict. None when nothing has been attributed yet."""
        with self._lock:
            comps = dict(self._components)
        comps = {k: v for k, v in comps.items() if v > 0}
        if not comps:
            return None
        return max(comps.items(), key=lambda kv: kv[1])[0]

    def forensics(self, last_samples: int = 20, buffers: int = 10) -> dict:
        """The memory section of a diagnostics bundle: recent samples,
        attribution, top live buffers, compile summary, and the suspect
        component."""
        with self._lock:
            peak = self._peak_live
        return {"enabled": True,
                "peak_bytes": int(peak),
                "components": self.components(),
                "suspect": self.suspect_component(),
                "recent_samples": self.samples(last=last_samples),
                "top_live_buffers": top_live_buffers(buffers),
                "compile": self.compile_stats()}

    def snapshot(self) -> dict:
        """Push payload for ``mem/rank{k}`` (compact: attribution +
        newest few samples + compile stats, not the whole ring)."""
        with self._lock:
            total = self._samples_total
            peak = self._peak_live
        recent = self.samples()
        live = recent[-1]["live_bytes"] if recent else 0
        return {"rank": self.rank, "ts": time.time(),
                "samples": total,
                "live_bytes": int(live), "peak_bytes": int(peak),
                "components": self.components(),
                "recent": recent[-5:],
                "compile": self.compile_stats()}

    def report(self) -> dict:
        """``hvd.memory_report()`` body for this rank."""
        out = self.snapshot()
        out["enabled"] = True
        out["capacity"] = self.capacity
        out["suspect"] = self.suspect_component()
        return out


# --------------------------------------------------------------------------
# Plan-build compile instrumentation (used by ops/collectives.py)
# --------------------------------------------------------------------------


class _CompileTimingWrapper:
    """First-call AOT compile probe around one jit-compiled callable.

    The first call lowers and compiles ahead-of-time inside a timed
    window (plan cache keys carry exact shapes/dtypes, so the compiled
    executable serves every later call), records the compile to the
    ledger, and reports the serialized program size to ``size_cb`` (the
    plan-cache byte accounting). Steady state is one attribute load plus
    the compiled executable — cheaper than jit's own dispatch, so the
    A/A overhead gate holds. Anything AOT cannot handle falls back to
    the original jit callable permanently.
    """

    __slots__ = ("_fn", "_kind", "_size_cb", "_target")

    def __init__(self, fn, kind: str,
                 size_cb: Optional[Callable[[int], None]] = None):
        self._fn = fn
        self._kind = kind
        self._size_cb = size_cb
        self._target = None

    def __call__(self, *args, **kw):
        if kw:
            # AOT specialization only covers positional calls; keyword
            # callers keep the original jit dispatch untouched
            return self._fn(*args, **kw)
        target = self._target
        if target is None:
            return self._first_call(args)
        try:
            return target(*args)
        except (TypeError, ValueError):
            # AOT signature drift (weak type / sharding changed between
            # calls): the retraceable jit fn takes over for good
            self._target = self._fn
            return self._fn(*args)

    def _first_call(self, args):
        fn = self._fn
        from . import compile_cache as compile_cache_mod

        cache_dir = compile_cache_mod.active_cache_dir()
        before = _cache_dir_entries(cache_dir) if cache_dir else -1
        t0 = time.perf_counter()
        try:
            compiled = fn.lower(*args).compile()
        except Exception:
            self._target = fn
            return fn(*args)
        seconds = time.perf_counter() - t0
        persistent = None
        if cache_dir and before >= 0:
            after = _cache_dir_entries(cache_dir)
            if after >= 0:
                persistent = "hit" if after <= before else "miss"
        nbytes = _program_bytes(compiled)
        self._target = compiled
        # byte accounting BEFORE the ledger record: record_compile takes
        # the plan_build memory sample, and that sample's plan_cache
        # component pull must already see this program's bytes
        if self._size_cb is not None and nbytes:
            try:
                self._size_cb(nbytes)
            except Exception:
                LOG.debug("plan size callback failed", exc_info=True)
        ledger = _LEDGER
        if ledger is not None:
            ledger.record_compile(self._kind, seconds, nbytes,
                                  persistent=persistent)
        return compiled(*args)


def accounting_armed() -> bool:
    """Whether plan builds should be instrumented: the ledger is on, or
    the plan-cache byte cap needs program sizes even without it. Called
    once per cache miss (cold)."""
    return (_LEDGER is not None
            or env_schema.get_int(env_schema.HOROVOD_PLAN_CACHE_MAX_BYTES,
                                  0) > 0)


def instrument_plan(plan, kind: str,
                    size_cb: Optional[Callable[[int], None]] = None):
    """Wrap the jit callables behind a freshly built plan with
    first-call compile accounting. Bare jitted functions are wrapped and
    returned; plan objects get their callable slots (``pack`` /
    ``quantize`` / ``run``) wrapped in place."""
    if plan is None:
        return plan
    if hasattr(plan, "lower") and callable(plan):
        return _CompileTimingWrapper(plan, kind, size_cb)
    for slot in ("pack", "quantize", "run"):
        fn = getattr(plan, slot, None)
        if fn is not None and hasattr(fn, "lower"):
            try:
                setattr(plan, slot, _CompileTimingWrapper(fn, kind, size_cb))
            except AttributeError:
                pass
    return plan


# --------------------------------------------------------------------------
# Process-global ledger (the utils/tracing.py module-trio pattern):
# get_ledger() returns None when HOROVOD_MEMLEDGER is off, and every hook
# site costs exactly one is-None check in that state.
# --------------------------------------------------------------------------

_LEDGER: Optional[MemLedger] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_MEMLEDGER)


def get_ledger() -> Optional[MemLedger]:
    return _LEDGER


def init_ledger(rank: int = 0) -> Optional[MemLedger]:
    """Create the process ledger when ``HOROVOD_MEMLEDGER`` is set
    (idempotent, like flightrec's init_recorder); no-op returning None
    when off."""
    global _LEDGER
    if not enabled():
        return _LEDGER
    if _LEDGER is None:
        capacity = env_schema.get_int(env_schema.HOROVOD_MEMLEDGER_BUFFER,
                                      DEFAULT_CAPACITY)
        _LEDGER = MemLedger(rank=rank, capacity=capacity)
    return _LEDGER


def reset_ledger() -> None:
    """Drop the process ledger (test/bench helper)."""
    global _LEDGER
    _LEDGER = None


def sample_event(event: str) -> None:
    """Cold-path convenience: take an event-driven sample iff the ledger
    is on (plan builds, elastic resizes, sharded-layout rebuilds)."""
    ledger = _LEDGER
    if ledger is None:
        return
    ledger.sample(event=event)


def note_sharded_state(state) -> None:
    """Measure the ZeRO-1 claim: attribute the (re)built sharded
    optimizer state's actual byte footprint and take a sample."""
    ledger = _LEDGER
    if ledger is None:
        return
    total = 0
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(state):
            total += int(getattr(leaf, "nbytes", 0) or 0)
    except Exception:
        return
    ledger.set_component("sharded_state", total)
    ledger.sample(event="sharded_state_build")


def forensics() -> dict:
    """Memory section for the diagnostics bundle: ``{"enabled": False}``
    plus a live-buffer table when the ledger is off (an OOM postmortem
    deserves the table even unattributed), the full forensics view when
    on."""
    ledger = _LEDGER
    if ledger is None:
        return {"enabled": False, "top_live_buffers": top_live_buffers(10)}
    return ledger.forensics()


def report() -> dict:
    """``hvd.memory_report()`` body: ``{"enabled": False}`` when the
    ledger is off, else this rank's samples/attribution/compile stats."""
    ledger = _LEDGER
    if ledger is None:
        return {"enabled": False}
    return ledger.report()
