"""Unified retry/backoff/deadline policy for the control plane.

Before this module, transient-fault handling was scattered and
inconsistent: the KV client retried a stale keep-alive socket exactly
once inline (runner/http_server.py), the controller fell back to one
flat 300 s blocking poll (ops/controller.py), and the elastic driver
blacklisted a host on its first failure (elastic/driver.py). Every
control-plane retry now goes through one :class:`Retrier`:

- **exponential backoff with full jitter** (AWS architecture-blog
  formulation: ``sleep = uniform(0, min(cap, base * mult**attempt))``) —
  full jitter because control-plane retries are synchronized across
  ranks by construction (everyone notices a store blip in the same
  round), exactly the thundering-herd shape jitter exists to break;
- **two deadlines**: per-policy ``max_attempts`` and an overall
  ``deadline_s`` — whichever is hit first ends the retry loop;
- **retryable classification**: by default only connection-level
  faults (``OSError`` / ``http.client.HTTPException``) are retried;
  everything else — auth failures, protocol bugs — propagates on the
  first throw;
- **metrics**: every attempt increments
  ``hvd_retry_attempts_total{site}``; running out of budget increments
  ``hvd_retry_exhausted_total{site}`` and re-raises the *last real
  exception*, so existing except-clauses keep working.

Global knobs (call sites pass their own defaults; env overrides both):

- ``HOROVOD_RETRY_MAX_ATTEMPTS`` — attempt budget per retried operation.
- ``HOROVOD_RETRY_DEADLINE`` — overall deadline (seconds) per operation.
- ``HOROVOD_RETRY_BASE_DELAY`` — first-backoff scale (seconds).
"""

from __future__ import annotations

import dataclasses
import http.client
import logging
import random
import time
from typing import Callable, Optional

from ..common import env as env_schema
from ..common.exceptions import RetriesExhaustedError

LOG = logging.getLogger("horovod_tpu")


def default_retryable(exc: BaseException) -> bool:
    """Connection-level faults only: a refused/reset/timed-out socket or
    a torn HTTP exchange is worth a retry; anything else (auth rejection,
    JSON garbage, programming errors) must propagate immediately."""
    return isinstance(exc, (OSError, http.client.HTTPException))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry budget + backoff shape for one class of operation.

    ``max_attempts=None`` means unbounded attempts (gate on
    ``deadline_s`` instead — the controller's response poll works this
    way); ``deadline_s=None`` means no overall deadline.
    """

    max_attempts: Optional[int] = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    deadline_s: Optional[float] = None
    retryable: Callable[[BaseException], bool] = default_retryable

    @classmethod
    def from_env(cls, **defaults) -> "RetryPolicy":
        """Site defaults overridden by the global env knobs (an operator
        mitigating an incident can widen every budget at once without a
        deploy)."""
        kw = dict(defaults)
        v = env_schema.get_int(env_schema.HOROVOD_RETRY_MAX_ATTEMPTS, -1)
        if v >= 1:
            kw["max_attempts"] = v
        d = env_schema.get_float(env_schema.HOROVOD_RETRY_DEADLINE, -1.0)
        if d > 0:
            kw["deadline_s"] = d
        b = env_schema.get_float(env_schema.HOROVOD_RETRY_BASE_DELAY, -1.0)
        if b > 0:
            kw["base_delay_s"] = b
        return cls(**kw)

    def backoff_delay(self, attempt: int,
                      rng: Optional[random.Random] = None) -> float:
        """Full-jitter delay before retry number ``attempt`` (1-based:
        the delay after the first failure is ``attempt=1``)."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** (attempt - 1))
        return (rng or _rng).uniform(0.0, cap)


_rng = random.Random()

# (site -> metric handles) resolved once per site, not per attempt
_metrics_cache: dict = {}


def _site_metrics(site: str):
    handles = _metrics_cache.get(site)
    if handles is None:
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        handles = (
            reg.counter("hvd_retry_attempts_total",
                        "control-plane operation attempts", site=site),
            reg.counter("hvd_retry_exhausted_total",
                        "operations that ran out of retry budget",
                        site=site),
        )
        _metrics_cache[site] = handles
    return handles


class Retrier:
    """Run a callable under a :class:`RetryPolicy`, labelled ``site``.

    ``sleep`` and ``rng`` are injectable for tests (a chaos suite must
    not spend wall-clock on backoff to prove backoff happened).
    """

    def __init__(self, site: str, policy: Optional[RetryPolicy] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        self.site = site
        self.policy = policy or RetryPolicy()
        self._sleep = sleep
        self._rng = rng
        self.attempts = 0  # observability for callers/tests

    def call(self, fn: Callable[[], object]):
        """Invoke ``fn`` until it returns, raises a non-retryable
        exception, or the budget (attempts/deadline) runs out — then the
        last exception re-raises."""
        pol = self.policy
        m_attempts, m_exhausted = _site_metrics(self.site)
        start = time.monotonic()
        attempt = 0
        while True:
            if (pol.deadline_s is not None and attempt > 0
                    and time.monotonic() - start >= pol.deadline_s):
                # deadline expired while backing off: budget is gone
                m_exhausted.inc()
                raise RetriesExhaustedError(
                    self.site, attempt, time.monotonic() - start)
            attempt += 1
            self.attempts = attempt
            m_attempts.inc()
            try:
                return fn()
            except Exception as e:
                if not pol.retryable(e):
                    raise
                elapsed = time.monotonic() - start
                out_of_attempts = (pol.max_attempts is not None
                                   and attempt >= pol.max_attempts)
                out_of_time = (pol.deadline_s is not None
                               and elapsed >= pol.deadline_s)
                if out_of_attempts or out_of_time:
                    m_exhausted.inc()
                    LOG.debug(
                        "%s: retry budget exhausted after %d attempt(s) / "
                        "%.1fs: %s", self.site, attempt, elapsed, e)
                    raise
                delay = pol.backoff_delay(attempt, self._rng)
                if pol.deadline_s is not None:
                    delay = min(delay, max(0.0, pol.deadline_s - elapsed))
                LOG.debug("%s: attempt %d failed (%s); retrying in %.3fs",
                          self.site, attempt, e, delay)
                from . import flightrec

                flightrec.note("retry_attempt", site=self.site,
                               attempt=attempt, delay_s=round(delay, 3))
                if delay > 0:
                    self._sleep(delay)


def call_with_retry(site: str, fn: Callable[[], object],
                    policy: Optional[RetryPolicy] = None):
    """One-shot convenience wrapper: ``Retrier(site, policy).call(fn)``."""
    return Retrier(site, policy).call(fn)
