"""Checkpoint helpers: pickle (default) or Orbax pytree format.

Reference analogue (SURVEY.md §5.4): the reference has no checkpoint
format of its own — rank-0-writes + broadcast, elastic State snapshots,
and the Spark Store. The TPU-native addition here is an Orbax-backed
pytree format (`orbax.checkpoint` is the standard JAX checkpoint layer):
elastic `JaxState` and user training loops can persist params/opt-state
trees in a format that interoperates with the wider JAX ecosystem and
scales to sharded multi-host arrays.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional


def have_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def _rm(path: str):
    import shutil

    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def save_pytree(path: str, tree: Any, *, format: str = "pickle"):
    """Crash-safe persist of a pytree. ``format``: "pickle" (single file)
    or "orbax" (directory; arrays in Orbax's tensorstore layout).

    Orbax directories cannot be atomically replaced the way a file can
    (``os.replace`` refuses non-empty dst dirs), so the sequence is
    write-tmp → rotate current to ``path + ".old"`` → rename tmp into
    place → drop the rotation. A crash in the middle leaves either the
    new tmp or the ``.old`` rotation on disk, and ``load_pytree``/
    ``exists`` fall back to ``.old`` — committed state is never lost.
    """
    # tmp names are pid-qualified: concurrent committers (elastic slots on
    # one host sharing HOROVOD_ELASTIC_STORE) must not interleave writes
    # into one tmp inode. The elastic State additionally writes only from
    # one rank per host, so this is defense in depth.
    if format == "orbax":
        import orbax.checkpoint as ocp

        tmp, old = f"{path}.tmp_ckpt.{os.getpid()}", path + ".old"
        _rm(tmp)
        ocp.PyTreeCheckpointer().save(tmp, tree)
        try:
            _rm(old)
            if os.path.exists(path):
                os.rename(path, old)
            os.rename(tmp, path)
            _rm(old)
        except OSError:
            # a concurrent committer won the rotation race (FileNotFoundError
            # when our source vanished; ENOTEMPTY/EEXIST when renaming onto
            # the winner's non-empty checkpoint dir); its snapshot is in
            # place — drop ours
            _rm(tmp)
        return
    if format != "pickle":
        raise ValueError(f"unknown checkpoint format {format!r}")
    from ..common.exceptions import FaultInjectedError
    from ..common.util import atomic_tmp
    from . import faults

    # Serialize first so the fault layer can tear the payload the way a
    # mid-write crash would, then write same-directory tmp + fsync +
    # rename: the committed path transitions valid → valid only.
    payload = pickle.dumps(tree)
    data = faults.corrupt("ckpt.write", payload)
    with atomic_tmp(path) as tmp:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if len(data) != len(payload):
            # a ckpt.write:torn rule fired: the "crash" happened after the
            # partial write and before the rename, so the tmp is discarded
            # and the committed checkpoint (if any) stays readable.
            raise FaultInjectedError(
                f"injected torn write at {path!r} (HOROVOD_FAULT_SPEC)")


def _resolve(path: str) -> str:
    """The live checkpoint path: ``path`` itself, or its ``.old`` rotation
    left by a crash mid-save."""
    if os.path.exists(path):
        return path
    if os.path.exists(path + ".old"):
        return path + ".old"
    return path


def load_pytree(path: str, *, format: Optional[str] = None) -> Any:
    """Load a checkpoint written by ``save_pytree``. ``format=None``
    auto-detects: a directory is Orbax, a file is pickle."""
    path = _resolve(path)
    if format is None:
        format = "orbax" if os.path.isdir(path) else "pickle"
    if format == "orbax":
        import orbax.checkpoint as ocp

        return ocp.PyTreeCheckpointer().restore(path)
    with open(path, "rb") as f:
        return pickle.load(f)


def exists(path: str) -> bool:
    return os.path.exists(path) or os.path.exists(path + ".old")
