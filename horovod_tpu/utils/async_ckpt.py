"""Preemption-tolerant async sharded checkpointing.

A preempted TPU-VM today loses all optimizer state: the PR 6 SIGTERM
hook (utils/diag.py) dumps diagnostics and dies. This module is the
durability layer on top of it. The ZeRO-1 sharded update
(opt/sharded.py, arXiv:2004.13336) already leaves each rank holding
exactly 1/N of the optimizer state, so checkpointing can be sharded,
parallel, and off the critical path: each rank snapshots *its own
shard* (plus the replicated leaves on rank 0), hands the host copy to a
background writer thread, and keeps training — the writer streams the
copy through utils/checkpoint.py atomically (same-directory tmp + fsync
+ rename) and stamps a per-rank manifest carrying the shard layout
digest, elastic generation, step, and payload checksum.

The hot path is bounded by a **snapshot-copy budget**: the only
synchronous work :meth:`AsyncCheckpointer.snapshot` does is the
device→host copy of this rank's shard; the write queue is depth-1 and
newest-wins, so a slow disk drops superseded snapshots
(``hvd_ckpt_dropped_total``) instead of ever blocking a step.

Preemption sequence (installed from ``hvd.init()`` AFTER the diag crash
hooks, so the chain runs durability-first): SIGTERM → flush the
in-flight + pending snapshot, deadline-bounded via utils/retry.py by
``HOROVOD_PREEMPT_GRACE_S`` → write the manifest → chain to the diag
bundle dump → previous disposition (the process still dies of SIGTERM).
The elastic driver forwards SIGTERM to workers and waits the same grace
window before escalating to SIGKILL (elastic/driver.py).

Restore (module functions — usable with the checkpointer off): the
newest *consistent* manifest set (every rank of one (step, generation,
layout-digest, world) present, checksums verified) names the snapshot;
same-world ranks reload their own shard bitwise, and an N→M resize
reassembles the full state by re-planning the saved world's layout
(``plan_shard_layout`` is deterministic — digest-checked against the
manifest), concatenating the shard leaves, and re-slicing through
:meth:`opt.sharded.ShardedUpdateEngine.load_full_state`.

Exposure: lazy ``hvd_ckpt_*`` series, ``checkpoint`` flightrec events,
a ``ckpt/rank{k}`` KV push on the MetricsDumper cadence merged by the
launcher's auth-exempt ``GET /checkpoint``.

Zero-cost contract (same as utils/anatomy.py, gated by
benchmarks/async_ckpt_overhead.py): with ``HOROVOD_ASYNC_CKPT`` unset
no checkpointer exists, hook sites pay one ``is None`` check, and no
``hvd_ckpt_*`` series is registered — metric handles are resolved in
``AsyncCheckpointer.__init__``, lazily at enable.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import signal
import threading
import time
from typing import Any, List, Optional, Tuple

from ..common import env as env_schema
from ..common.exceptions import FaultInjectedError
from . import faults, flightrec, lockcheck

LOG = logging.getLogger("horovod_tpu")

#: KV scope the MetricsDumper pushes per-rank checkpoint status under
#: (``ckpt/rank{k}``); the launcher's ``GET /checkpoint`` merges it.
KV_SCOPE = "ckpt"

DEFAULT_DIR = "./horovod_ckpt"

_SHARD_FMT = "shard_rank{rank}.ckpt"
_MANIFEST_FMT = "manifest_rank{rank}.json"
_MANIFEST_RE = re.compile(r"manifest_rank(\d+)\.json$")


class CheckpointError(RuntimeError):
    """A checkpoint directory is unreadable, inconsistent, or fails its
    checksum — restore callers decide whether to fall back to a cold
    start (the elastic path does) or surface it."""


def _sha1_file(path: str) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _to_host(tree):
    """Host-numpy copy of a (possibly device-backed) pytree: device
    buffers do not survive the TPU re-initialization a preemption causes
    (elastic/state.py makes the same argument for its snapshots)."""
    import copy

    import jax
    import numpy as np

    return jax.tree.map(
        lambda x: np.asarray(x).copy() if hasattr(x, "dtype")
        else copy.deepcopy(x), tree)


class AsyncCheckpointer:
    """Per-rank async shard writer with a depth-1, newest-wins queue.

    ``snapshot()`` is the training-loop hook: host-copy + enqueue, never
    disk. The daemon writer commits each accepted snapshot as an atomic
    shard file + manifest; ``preempt_flush()`` drains synchronously
    under a deadline (the SIGTERM path).
    """

    def __init__(self, rank: int = 0, world: int = 1,
                 directory: Optional[str] = None):
        self.rank = int(rank)
        self.world = int(world)
        self.directory = (directory
                          or env_schema.get_str(
                              env_schema.HOROVOD_ASYNC_CKPT_DIR)
                          or DEFAULT_DIR)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = lockcheck.make_lock("async_ckpt.state")
        self._pending: Optional[dict] = None  # guarded-by: _lock
        self._inflight = False  # guarded-by: _lock
        self._wakeup = threading.Event()
        self._stop = threading.Event()
        # freshest status for report()/KV pushes and the bench extras
        self.last_copy_s = 0.0
        self.last_write_s = 0.0
        self.last_restore_s = 0.0
        self.last_shard_bytes = 0
        self.last_step: Optional[int] = None
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._m_snapshots = reg.counter(
            "hvd_ckpt_snapshots_total",
            "shard snapshots accepted by the async checkpointer")
        self._m_dropped = reg.counter(
            "hvd_ckpt_dropped_total",
            "snapshots superseded before the writer committed them "
            "(the snapshot-copy budget: newest wins, training never blocks)")
        self._m_commits = reg.counter(
            "hvd_ckpt_commits_total",
            "shard checkpoint files committed (tmp + fsync + rename)")
        self._m_failures = reg.counter(
            "hvd_ckpt_failures_total",
            "shard checkpoint commits that failed (kept training)")
        self._m_bytes = reg.counter(
            "hvd_ckpt_bytes_total", "committed shard checkpoint bytes")
        self._m_write = reg.histogram(
            "hvd_ckpt_write_seconds",
            "background shard commit duration (shard file + manifest)",
            buckets=metrics_mod.LATENCY_BUCKETS_S)
        self._m_last_step = reg.gauge(
            "hvd_ckpt_last_step", "newest durably committed step")
        self._m_restores = reg.counter(
            "hvd_ckpt_restores_total",
            "shard-checkpoint restores served (incl. N->M re-slices)")
        self._thread = threading.Thread(
            target=self._writer_loop, daemon=True, name="hvd-async-ckpt")
        self._thread.start()

    # -- hot path -----------------------------------------------------------

    def snapshot(self, step: int, shard: Any, *,
                 replicated: Any = None, layout=None,
                 generation: Optional[int] = None) -> bool:
        """Accept one snapshot: ``shard`` is this rank's own slice of
        state (under ZeRO-1, the per-rank combined optimizer state —
        already 1/N), ``replicated`` the full replicated leaves (pass on
        rank 0 only; other ranks' copies are identical by contract).
        ``layout`` (a ShardLayout) stamps the digest that invalidates
        the snapshot across reshards. Returns False when this snapshot
        displaced a pending, not-yet-written one (slow disk)."""
        t0 = time.perf_counter()
        if generation is None:
            generation = env_schema.get_int(env_schema.HOROVOD_ELASTIC_GEN, 0)
        job = {
            "rank": self.rank,
            "world": self.world,
            "step": int(step),
            "generation": int(generation),
            "layout_digest": getattr(layout, "digest", "") or "",
            "shard_state": _to_host(shard),
            "replicated": _to_host(replicated)
            if replicated is not None else None,
        }
        self.last_copy_s = time.perf_counter() - t0
        with self._lock:
            displaced = self._pending is not None
            self._pending = job
        self._m_snapshots.inc()
        if displaced:
            self._m_dropped.inc()
        self._wakeup.set()
        return not displaced

    # -- background writer --------------------------------------------------

    def _writer_loop(self):
        while not self._stop.is_set():
            self._wakeup.wait(timeout=0.2)
            self._wakeup.clear()
            self._drain()

    def _take(self) -> Optional[dict]:
        with self._lock:
            job = self._pending
            self._pending = None
            if job is not None:
                self._inflight = True
            return job

    def _done(self):
        with self._lock:
            self._inflight = False

    def _drain(self):
        while True:
            job = self._take()
            if job is None:
                return
            try:
                self._commit(job)
            except Exception as e:
                # checkpointing is opt-in durability: a failed commit is
                # loud but must never take the training job down
                self._m_failures.inc()
                flightrec.note("checkpoint", event="commit_failed",
                               step=job["step"], error=type(e).__name__)
                LOG.warning("async ckpt: commit of step %d failed: %s",
                            job["step"], e)
            finally:
                self._done()

    def _commit(self, job: dict):
        t0 = time.perf_counter()
        faults.fault_point("ckpt.write")
        from . import checkpoint as ckpt_mod

        shard_path = os.path.join(
            self.directory, _SHARD_FMT.format(rank=job["rank"]))
        ckpt_mod.save_pytree(shard_path, job)
        nbytes = os.path.getsize(shard_path)
        manifest = {
            "rank": job["rank"],
            "world": job["world"],
            "step": job["step"],
            "generation": job["generation"],
            "layout_digest": job["layout_digest"],
            "checksum": _sha1_file(shard_path),
            "bytes": nbytes,
            "ts": time.time(),
        }
        from ..common.util import atomic_write_bytes

        atomic_write_bytes(
            os.path.join(self.directory,
                         _MANIFEST_FMT.format(rank=job["rank"])),
            json.dumps(manifest).encode())
        dt = time.perf_counter() - t0
        self.last_write_s = dt
        self.last_shard_bytes = nbytes
        self.last_step = job["step"]
        self._m_commits.inc()
        self._m_bytes.inc(nbytes)
        self._m_write.observe(dt)
        self._m_last_step.set(job["step"])
        flightrec.note("checkpoint", event="commit", step=job["step"],
                       generation=job["generation"], bytes=nbytes,
                       digest=(job["layout_digest"] or "")[:12])

    # -- flush (SIGTERM / shutdown path) ------------------------------------

    def flush(self, deadline_s: Optional[float] = None) -> bool:
        """Drain the in-flight and pending snapshot synchronously,
        bounded by ``deadline_s``. Returns True when everything accepted
        so far is durable on disk."""
        faults.fault_point("ckpt.flush")
        start = time.monotonic()

        def _left() -> Optional[float]:
            if deadline_s is None:
                return None
            return max(deadline_s - (time.monotonic() - start), 0.0)

        # wait out a commit the writer thread already started
        while True:
            with self._lock:
                busy = self._inflight
            if not busy:
                break
            left = _left()
            if left is not None and left <= 0:
                return False
            time.sleep(0.01)
        job = self._take()
        if job is None:
            self._done()
            return True
        from .retry import RetryPolicy, call_with_retry

        policy = RetryPolicy.from_env(
            max_attempts=3, base_delay_s=0.05, deadline_s=_left(),
            retryable=lambda e: isinstance(e, (OSError, FaultInjectedError)))
        try:
            call_with_retry("ckpt.flush", lambda: self._commit(job), policy)
            return True
        except Exception as e:
            self._m_failures.inc()
            LOG.warning("async ckpt: flush of step %d failed: %s",
                        job["step"], e)
            return False
        finally:
            self._done()

    def preempt_flush(self, deadline_s: float) -> bool:
        """The SIGTERM handler body: flush under the grace budget and
        leave a breadcrumb either way."""
        flightrec.note("checkpoint", event="preempt",
                       deadline_s=round(deadline_s, 3))
        ok = self.flush(deadline_s=deadline_s)
        flightrec.note("checkpoint", event="preempt_flushed", ok=ok,
                       step=self.last_step)
        return ok

    def stop(self):
        """Shut the writer down after a best-effort flush (reset/test
        helper; the preemption path uses :meth:`preempt_flush`)."""
        self.flush(deadline_s=5.0)
        self._stop.set()
        self._wakeup.set()
        self._thread.join(timeout=5.0)

    # -- readers ------------------------------------------------------------

    def snapshot_status(self) -> dict:
        """Push payload for ``ckpt/rank{k}`` and the ``GET /checkpoint``
        merge."""
        with self._lock:
            queued = self._pending is not None
            inflight = self._inflight
        return {"rank": self.rank, "world": self.world,
                "dir": self.directory,
                "last_step": self.last_step,
                "last_write_s": round(self.last_write_s, 6),
                "last_copy_s": round(self.last_copy_s, 6),
                "last_restore_s": round(self.last_restore_s, 6),
                "last_shard_bytes": self.last_shard_bytes,
                "queued": queued, "inflight": inflight}

    def report(self) -> dict:
        out = self.snapshot_status()
        out["enabled"] = True
        return out


# --------------------------------------------------------------------------
# Restore: module functions, independent of the enable knob (a cold
# restart must be able to read shards written by its previous life even
# before hvd.init() re-creates a checkpointer).
# --------------------------------------------------------------------------


def read_manifest(directory: str) -> Optional[dict]:
    """The newest *consistent* snapshot in ``directory``: per-rank
    manifests grouped by (step, generation, layout digest, world); a
    group wins only when every rank of its world is present (a stale
    shard from a previous, larger world can never join it). Returns
    ``{"step", "generation", "layout_digest", "world", "ranks": {...}}``
    or None when no complete snapshot exists."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    entries: dict = {}
    for name in names:
        m = _MANIFEST_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name), "rb") as f:
                entry = json.loads(f.read())
        except (OSError, ValueError):
            continue  # half-written manifest: the shard never committed
        if int(entry.get("rank", -1)) != int(m.group(1)):
            continue
        key = (int(entry.get("step", -1)), int(entry.get("generation", 0)),
               str(entry.get("layout_digest", "")), int(entry.get("world", 0)))
        entries.setdefault(key, {})[int(entry["rank"])] = entry
    best = None
    for (step, gen, digest, world), ranks in entries.items():
        if world <= 0 or set(ranks) != set(range(world)):
            continue  # incomplete: some rank never flushed this step
        if best is None or step > best["step"]:
            best = {"step": step, "generation": gen,
                    "layout_digest": digest, "world": world, "ranks": ranks}
    return best


def load_shards(directory: str, *,
                verify: bool = True) -> Tuple[dict, List[dict]]:
    """Load the newest consistent snapshot's per-rank shard payloads,
    rank order. ``verify`` checks each shard file's sha1 against its
    manifest (a torn write that somehow got committed fails here, not
    as optimizer-state garbage)."""
    manifest = read_manifest(directory)
    if manifest is None:
        raise CheckpointError(
            f"no complete checkpoint in {directory!r} "
            "(missing or inconsistent per-rank manifests)")
    from . import checkpoint as ckpt_mod

    payloads: List[dict] = []
    for rank in range(manifest["world"]):
        entry = manifest["ranks"][rank]
        path = os.path.join(directory, _SHARD_FMT.format(rank=rank))
        if verify:
            digest = _sha1_file(path)
            if digest != entry["checksum"]:
                raise CheckpointError(
                    f"checksum mismatch for rank {rank} shard {path!r}: "
                    f"manifest {entry['checksum'][:12]} != file {digest[:12]}")
        payload = ckpt_mod.load_pytree(path)
        if (int(payload.get("step", -1)) != manifest["step"]
                or payload.get("layout_digest", "")
                != manifest["layout_digest"]):
            raise CheckpointError(
                f"rank {rank} shard {path!r} disagrees with its manifest "
                "(step/layout digest)")
        payloads.append(payload)
    return manifest, payloads


def assemble_full_state(manifest: dict, payloads: List[dict], params, *,
                        min_shard_elems: Optional[int] = None):
    """Reassemble the unsharded optimizer state from saved shards: the
    saved world's layout is re-planned deterministically (digest-checked
    against the manifest — a threshold or tree change since the save is
    refused, not silently mis-sliced), shard leaves concatenate across
    ranks and trim to their group's true extent, replicated leaves come
    from rank 0. The disk-backed analogue of
    ``opt.sharded.simulated_full_state``."""
    import numpy as np
    from jax import tree_util as jtu

    from ..opt.sharded import _shard_group_for, plan_shard_layout

    layout = plan_shard_layout(params, manifest["world"],
                               min_shard_elems=min_shard_elems,
                               generation=manifest["generation"])
    if manifest["layout_digest"] and layout.digest != manifest["layout_digest"]:
        raise CheckpointError(
            f"saved layout digest {manifest['layout_digest'][:12]} does not "
            f"reproduce ({layout.digest[:12]}): params tree or shard "
            "threshold changed since the checkpoint was written")
    states = [p["shard_state"] for p in payloads]
    flats = [jtu.tree_flatten_with_path(s) for s in states]
    treedef = flats[0][1]
    out = []
    for pos, (path, leaf) in enumerate(flats[0][0]):
        g = _shard_group_for(layout, path, leaf)
        if g is not None:
            full = np.concatenate(
                [np.ravel(np.asarray(flats[r][0][pos][1]))
                 for r in range(manifest["world"])])
            out.append(full[:g.total])
        else:
            out.append(leaf)
    return jtu.tree_unflatten(treedef, out)


def restore_sharded(directory: str, params, engine, *,
                    verify: bool = True) -> Tuple[dict, Any, Any]:
    """Restore a ZeRO-1 engine's per-rank state from a shard checkpoint,
    re-slicing through the engine's *current* layout — the saved world
    and the restoring world may differ (N→M resize). Returns
    ``(manifest, state_for_this_rank, replicated)`` where ``replicated``
    is rank 0's saved replicated tree (None when the writer passed
    none)."""
    t0 = time.perf_counter()
    manifest, payloads = load_shards(directory, verify=verify)
    mse = getattr(engine, "_mse", None)
    full = assemble_full_state(manifest, payloads, params,
                               min_shard_elems=mse)
    state = engine.load_full_state(full, params)
    ckpt = get_checkpointer()
    if ckpt is not None:
        ckpt._m_restores.inc()
        ckpt.last_restore_s = time.perf_counter() - t0
    flightrec.note("checkpoint", event="restore", step=manifest["step"],
                   saved_world=manifest["world"],
                   world=getattr(engine, "_world", None))
    return manifest, state, payloads[0].get("replicated")


def load_own_shard(directory: str, rank: int, *,
                   verify: bool = True) -> Optional[dict]:
    """Same-world fast path: this rank's saved payload verbatim (bitwise
    state), or None when the newest consistent snapshot was written by a
    different world size or does not cover ``rank``."""
    try:
        manifest, payloads = load_shards(directory, verify=verify)
    except CheckpointError:
        return None
    if rank >= manifest["world"]:
        return None
    return payloads[rank]


# --------------------------------------------------------------------------
# Preemption handler: SIGTERM → deadline-bounded flush → chain to the
# previously installed handler (the diag bundle dump, which itself
# chains to the default disposition — the process still dies).
# --------------------------------------------------------------------------

_handler_installed = False


def install_preemption_handler(ckpt: AsyncCheckpointer) -> None:
    """Install after diag.install_crash_hooks() (common/context.py
    ordering) so the chain runs flush-first, dump-second. Idempotent;
    best-effort off the main thread."""
    global _handler_installed
    if _handler_installed:
        return
    _handler_installed = True
    sig = getattr(signal, "SIGTERM", None)
    if sig is None:
        return
    try:
        prev = signal.getsignal(sig)

        def _handler(signum, frame):
            grace = env_schema.get_float(
                env_schema.HOROVOD_PREEMPT_GRACE_S, 15.0)
            # leave headroom inside the driver's grace window for the
            # chained diag dump before SIGKILL lands
            c = get_checkpointer()
            if c is not None:
                c.preempt_flush(deadline_s=max(grace * 0.8, 1.0))
            if callable(prev):
                prev(signum, frame)
            elif prev != signal.SIG_IGN:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        signal.signal(sig, _handler)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass


def reset_preemption_handler_for_tests() -> None:
    """Allow a test subprocess to re-install the handler (NOT an
    uninstall)."""
    global _handler_installed
    _handler_installed = False


# --------------------------------------------------------------------------
# Process-global checkpointer (the utils/anatomy.py module-trio pattern):
# get_checkpointer() returns None when HOROVOD_ASYNC_CKPT is off, and
# every hook site costs exactly one is-None check in that state.
# --------------------------------------------------------------------------

_CHECKPOINTER: Optional[AsyncCheckpointer] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_ASYNC_CKPT)


def get_checkpointer() -> Optional[AsyncCheckpointer]:
    return _CHECKPOINTER


def init_checkpointer(rank: int = 0,
                      world: int = 1) -> Optional[AsyncCheckpointer]:
    """Create the process checkpointer when ``HOROVOD_ASYNC_CKPT`` is
    set (idempotent) and wire the SIGTERM preemption handler; no-op
    returning None when off."""
    global _CHECKPOINTER
    if not enabled():
        return _CHECKPOINTER
    if _CHECKPOINTER is None:
        _CHECKPOINTER = AsyncCheckpointer(rank=rank, world=world)
        install_preemption_handler(_CHECKPOINTER)
    return _CHECKPOINTER


def reset_checkpointer() -> None:
    """Stop and drop the process checkpointer (test/bench helper)."""
    global _CHECKPOINTER
    if _CHECKPOINTER is not None:
        _CHECKPOINTER.stop()
    _CHECKPOINTER = None


def report() -> dict:
    """``hvd.checkpoint_report()`` body: ``{"enabled": False}`` when the
    checkpointer is off, else this rank's write/flush status."""
    ckpt = _CHECKPOINTER
    if ckpt is None:
        return {"enabled": False}
    return ckpt.report()
