"""Stall inspector: detect tensors stuck in the pending queue.

Reference: /root/reference/horovod/common/stall_inspector.{h,cc} — the
coordinator warns when some ranks submitted a tensor while others have not
for 60 s (`CheckForStalledTensors`, stall_inspector.h:39), and optionally
shuts the job down after ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

On TPU the compiled path cannot stall this way (one SPMD program), so the
inspector watches the *eager async* queue: a tensor enqueued but not executed
for ``warning_time_s`` (default 60, same as reference) triggers a warning;
``shutdown_time_s > 0`` escalates to `StalledTensorError`, failing pending
work like the reference's forced shutdown.
"""

from __future__ import annotations

import logging
import time

from ..common.exceptions import StalledTensorError

LOG = logging.getLogger("horovod_tpu")


class StallInspector:
    def __init__(self, warning_time_s: float = 60.0, shutdown_time_s: float = 0.0,
                 disabled: bool = False):
        self.warning_time_s = warning_time_s
        self.shutdown_time_s = shutdown_time_s
        self.disabled = disabled
        self._pending: dict[str, float] = {}
        self._warned: set[str] = set()

    def record_pending(self, name: str):
        self._pending.setdefault(name, time.monotonic())

    def record_done(self, name: str):
        self._pending.pop(name, None)
        self._warned.discard(name)

    def check(self):
        """Called once per background cycle (reference: invoked from
        ComputeResponseList, controller.cc:294)."""
        if self.disabled or not self._pending:
            return
        now = time.monotonic()
        stalled = [(n, now - t) for n, t in self._pending.items()
                   if now - t > self.warning_time_s]
        for name, age in stalled:
            if name not in self._warned:
                LOG.warning(
                    "Tensor %s has been pending for %.0f s without executing. "
                    "This may indicate that not all processes are submitting "
                    "the same collectives in the same order.", name, age)
                self._warned.add(name)
        if self.shutdown_time_s > 0:
            dead = [n for n, t in self._pending.items()
                    if now - t > self.shutdown_time_s]
            if dead:
                err = StalledTensorError(
                    f"tensors stalled beyond shutdown time: {sorted(dead)}")
                err.names = sorted(dead)
                raise err
