"""Stall inspector: detect tensors stuck in the pending queue.

Reference: /root/reference/horovod/common/stall_inspector.{h,cc} — the
coordinator warns when some ranks submitted a tensor while others have not
for 60 s (`CheckForStalledTensors`, stall_inspector.h:39), and optionally
shuts the job down after ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

On TPU the compiled path cannot stall this way (one SPMD program), so the
inspector watches the *eager async* queue: a tensor enqueued but not executed
for ``warning_time_s`` (default 60, same as reference) triggers a warning;
``shutdown_time_s > 0`` escalates to `StalledTensorError`, failing pending
work like the reference's forced shutdown.

Wired into the metrics registry (utils/metrics.py): the oldest pending age
is a gauge a scraper can alert on *before* the warning threshold, and
warning/shutdown escalations are counters — the post-mortem signal the
BENCH_r05 wedged-backend hang had no way to emit.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..common.exceptions import StalledTensorError
from . import metrics as metrics_mod

LOG = logging.getLogger("horovod_tpu")


def _age_distribution(ages: list[float]) -> str:
    """Compact pending-queue age summary for the warning message:
    count + min/median/max, enough to tell one straggler from a wedge."""
    if not ages:
        return "no tensors pending"
    s = sorted(ages)
    return (f"{len(s)} pending (age min/median/max = "
            f"{s[0]:.1f}/{s[len(s) // 2]:.1f}/{s[-1]:.1f} s)")


class StallInspector:
    def __init__(self, warning_time_s: float = 60.0, shutdown_time_s: float = 0.0,
                 disabled: bool = False):
        self.warning_time_s = warning_time_s
        self.shutdown_time_s = shutdown_time_s
        self.disabled = disabled
        self._pending: dict[str, float] = {}
        self._warned: set[str] = set()
        # most recent straggler attribution from the coordinator (tracing
        # on): (rank, tensor name, wait_s, monotonic time). A stall
        # warning that can name the suspect rank beats one that can only
        # name the stuck tensor.
        self._last_straggler: Optional[tuple] = None
        reg = metrics_mod.get_registry()
        self._m_oldest = reg.gauge(
            "hvd_stall_oldest_pending_age_seconds",
            "age of the oldest tensor still waiting to execute")
        self._m_pending = reg.gauge(
            "hvd_stall_pending_tensors", "tensors in the pending table")
        self._m_warnings = reg.counter(
            "hvd_stall_warnings_total", "stall warnings emitted")
        self._m_stalled = reg.counter(
            "hvd_stall_stalled_tensors_total",
            "tensors that crossed the warning threshold")
        self._m_shutdowns = reg.counter(
            "hvd_stall_shutdowns_total",
            "warning-to-shutdown escalations (StalledTensorError raised)")

    def record_pending(self, name: str):
        self._pending.setdefault(name, time.monotonic())

    def record_done(self, name: str):
        self._pending.pop(name, None)
        self._warned.discard(name)

    def note_straggler(self, name: str, rank: int, wait_s: float):
        """Record the coordinator's latest straggler attribution (fed by
        the negotiation response when tracing is on)."""
        self._last_straggler = (rank, name, wait_s, time.monotonic())

    # attribution staler than this is history, not a lead on the current
    # stall — keep it out of the warning text
    STRAGGLER_FRESH_S = 300.0

    def _suspect(self) -> str:
        if self._last_straggler is None:
            return ""
        rank, name, wait_s, t = self._last_straggler
        if time.monotonic() - t > self.STRAGGLER_FRESH_S:
            return ""
        return (f" Straggler attribution: rank {rank} was last to submit "
                f"{name!r} (peers waited {wait_s:.3f} s); suspect that "
                "rank first.")

    def note_slo_breach(self, budget: str, detail: str):
        """Escalate an SLO-budget breach (utils/perfledger.py) through the
        same warning path a stalled tensor takes — the breach names the
        violated budget and, when the coordinator attributed a recent
        straggler, the suspect rank."""
        LOG.warning("SLO budget %r breached: %s.%s", budget, detail,
                    self._suspect())
        self._m_warnings.inc()

    def straggler_rank(self) -> Optional[int]:
        """The last coordinator-attributed straggler rank, or None when
        attribution is absent or stale (same freshness window the text
        suspect line uses) — the health engine's suspect_rank source."""
        if self._last_straggler is None:
            return None
        rank, _, _, t = self._last_straggler
        if time.monotonic() - t > self.STRAGGLER_FRESH_S:
            return None
        return rank

    def note_health_anomaly(self, series: str, detail: str):
        """Escalate a latched fleet-health anomaly (utils/health.py)
        through the same warning path an SLO breach takes — naming the
        drifted series, observed-vs-baseline, and (when the coordinator
        attributed a recent straggler) the suspect rank."""
        LOG.warning("Health anomaly on %r: %s.%s", series, detail,
                    self._suspect())
        self._m_warnings.inc()

    def check(self):
        """Called once per background cycle (reference: invoked from
        ComputeResponseList, controller.cc:294)."""
        if self.disabled:
            return
        if not self._pending:
            self._m_oldest.set(0.0)
            self._m_pending.set(0)
            return
        now = time.monotonic()
        ages = [now - t for t in self._pending.values()]
        self._m_oldest.set(max(ages))
        self._m_pending.set(len(ages))
        stalled = [(n, now - t) for n, t in self._pending.items()
                   if now - t > self.warning_time_s]
        dist = _age_distribution(ages) if stalled else ""
        suspect = self._suspect() if stalled else ""
        for name, age in stalled:
            if name not in self._warned:
                LOG.warning(
                    "Tensor %s has been pending for %.0f s without executing. "
                    "This may indicate that not all processes are submitting "
                    "the same collectives in the same order. Queue: %s.%s",
                    name, age, dist, suspect)
                self._warned.add(name)
                self._m_warnings.inc()
                self._m_stalled.inc()
        if self.shutdown_time_s > 0:
            dead = [n for n, t in self._pending.items()
                    if now - t > self.shutdown_time_s]
            if dead:
                self._m_shutdowns.inc()
                err = StalledTensorError(
                    f"tensors stalled beyond shutdown time: {sorted(dead)}")
                err.names = sorted(dead)
                raise err
