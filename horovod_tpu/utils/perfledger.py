"""Per-step performance ledger + declarative SLO budget engine.

The metrics registry answers "how much/how fast overall" and the tracer
answers "when did each collective run", but neither answers the
steady-state question "where does *each step's* time and bandwidth go,
and is it getting worse?" — the joint signal the autotuner (ROADMAP
item 4) and the controller-scaling budget gate (ROADMAP item 3) both
need. This module is that signal: a bounded ring of per-step records
assembled from observations that already exist (cycle-phase stamps fed
by ops/queue.py, ``hvd_*_wire_bytes_total`` counter deltas, plan-cache
hit/miss, staging-ring reuse, coordinator straggler verdicts).

Each record decomposes one background-cycle step's wall time into five
phases — negotiate / fuse_dispatch / device_exec / stall /
host_overhead — and each snapshot derives goodput numbers from the ring
(effective allreduce GB/s, exposed-comm fraction, wire bytes per step,
plan hit rate). Exposure: lazy ``hvd_perf_*`` series, the
``hvd.perf_report()`` API, and a ``perf/rank{k}`` KV push (rides the
MetricsDumper cadence) merged by the launcher's ``GET /perf``.

The SLO budget engine turns the same stats into a live gate: budgets
declared via ``HOROVOD_SLO_SPEC`` (inline grammar
``negotiate_p95_ms<=5,plan_hit_rate>=0.95``, an inline JSON object, or
a path to a JSON file) are evaluated over each new window of records on
the MetricsDumper cadence. A breach fires once per breach window (the
budget re-arms when a later window is back within bound): it increments
``hvd_slo_breach_total{budget}``, notes a ``slo_breach`` flight-recorder
event, and escalates through the stall-warning path naming the violated
budget and the suspect rank.

Zero-cost contract (same as utils/tracing.py and utils/flightrec.py,
enforced by hvdlint's zero-cost-hooks rule and
benchmarks/perfledger_overhead.py): with ``HOROVOD_PERFLEDGER`` unset no
ledger exists, hot paths pay one ``is None`` check per hook, and no
``hvd_perf_*``/``hvd_slo_*`` series is registered. Metric handles are
resolved in ``PerfLedger.__init__`` / ``SloEngine.__init__`` — lazily at
enable — so the off state adds zero series.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import List, Optional, Tuple

from ..common import env as env_schema
from . import flightrec as flightrec_mod
from . import lockcheck

LOG = logging.getLogger("horovod_tpu")

#: KV scope the MetricsDumper pushes per-rank ledger snapshots under
#: (``perf/rank{k}``); the launcher's ``GET /perf`` merges the scope.
KV_SCOPE = "perf"

DEFAULT_CAPACITY = 1024

#: The five phases every step's wall time is decomposed into. ``stall``
#: is the slice of the negotiation round spent waiting on a coordinator-
#: attributed straggler (zero when this rank *was* the straggler — its
#: round time is its own negotiate phase, not exposed waiting).
PHASES = ("negotiate", "fuse_dispatch", "device_exec", "stall",
          "host_overhead")

#: Counters whose per-step deltas each record carries: (record key,
#: metric family). Reads go through ``MetricsRegistry.counter_value``,
#: which sums across label sets, so the dtype-labelled byte counters
#: collapse to one number per step.
_DELTA_COUNTERS = (
    ("wire_bytes", "hvd_allreduce_bytes_total"),
    ("control_bytes", "hvd_controller_wire_bytes_total"),
    ("plan_hits", "hvd_fused_plan_hits_total"),
    ("plan_misses", "hvd_fused_plan_misses_total"),
    ("staging_reuse", "hvd_staging_reuse_total"),
)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (same
    convention as utils/tracing.py so /perf and /timeline agree)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class PerfLedger:
    """Bounded ring of per-step phase/goodput records.

    ``record_step()`` is the only hot method and is called once per
    *working* background cycle (idle cycles don't record) from the cycle
    thread; readers copy the ring under the lock.
    """

    def __init__(self, rank: int = 0, capacity: int = DEFAULT_CAPACITY):
        self.rank = rank
        self.capacity = max(int(capacity), 16)
        self._lock = lockcheck.make_lock("perfledger.ring")
        self._ring = collections.deque(maxlen=self.capacity)  # guarded-by: _lock
        self._total = 0  # guarded-by: _lock
        # compile seconds handed over by the memledger (utils/memledger
        # record_compile) since the last recorded step — a recompile
        # storm must show up as host overhead, not silent exec time
        self._compile_pending = 0.0  # guarded-by: _lock
        # counter baselines for per-step deltas; cycle-thread-only
        self._last_counters: dict = {}
        # running sums behind the goodput gauges (process lifetime, not
        # ring-windowed: a gauge that forgets history on wraparound lies)
        self._sum_wall = 0.0  # guarded-by: _lock
        self._sum_comm = 0.0  # guarded-by: _lock
        self._sum_exec = 0.0  # guarded-by: _lock
        self._sum_wire = 0.0  # guarded-by: _lock
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._m_steps = reg.counter(
            "hvd_perf_steps_total", "steps recorded by the perf ledger")
        self._m_step_s = reg.histogram(
            "hvd_perf_step_seconds", "per-step wall time",
            buckets=metrics_mod.LATENCY_BUCKETS_S)
        self._m_phase = {
            p: reg.histogram(
                "hvd_perf_phase_seconds",
                "per-step wall time attributed to one phase",
                buckets=metrics_mod.LATENCY_BUCKETS_S, phase=p)
            for p in PHASES}
        self._m_wire = reg.histogram(
            "hvd_perf_step_wire_bytes", "data-plane wire bytes per step",
            buckets=metrics_mod.SIZE_BUCKETS_BYTES)
        self._m_exposed = reg.gauge(
            "hvd_perf_exposed_comm_ratio",
            "fraction of recorded wall time exposed to communication "
            "(negotiate + stall phases)")
        self._m_gbps = reg.gauge(
            "hvd_perf_allreduce_gbps",
            "effective allreduce goodput: wire bytes over device-exec "
            "seconds")
        self._m_hit = reg.gauge(
            "hvd_perf_plan_hit_rate",
            "cumulative fused-plan cache hit rate seen by the ledger")

    def note_compile(self, seconds: float) -> None:
        """Attribute one XLA compile's wall time to the next recorded
        step (called by the memledger's compile instrumentation; rare by
        construction — once per plan program)."""
        with self._lock:
            self._compile_pending += max(float(seconds), 0.0)

    def _counter_deltas(self) -> dict:
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        sums = reg.counter_values([f for _, f in _DELTA_COUNTERS])
        out = {}
        for key, family in _DELTA_COUNTERS:
            cur = sums[family]
            prev = self._last_counters.get(key, cur)
            self._last_counters[key] = cur
            # registry resets (tests) would otherwise show as a huge
            # negative step; clamp to zero instead
            out[key] = max(cur - prev, 0.0)
        return out

    def record_step(self, wall_s: float, negotiate_s: float = 0.0,
                    dispatch_s: float = 0.0, exec_s: float = 0.0,
                    tensors: int = 0,
                    straggler: Optional[Tuple[int, float]] = None) -> dict:
        """Append one step record.

        ``negotiate_s`` is the negotiation-round wall time,
        ``dispatch_s`` the whole dispatch-loop host time and ``exec_s``
        the execute window inside it; ``straggler`` is the coordinator's
        ``(rank, wait_s)`` verdict for this round when tracing computed
        one. The phase split and counter deltas are derived here so the
        queue hook stays four perf_counter() reads.
        """
        wall_s = max(float(wall_s), 0.0)
        negotiate_s = min(max(float(negotiate_s), 0.0), wall_s)
        dispatch_s = max(float(dispatch_s), 0.0)
        exec_s = min(max(float(exec_s), 0.0), dispatch_s)
        stall_s = 0.0
        strag_rank = None
        strag_wait = 0.0
        if straggler is not None:
            strag_rank = int(straggler[0])
            strag_wait = max(float(straggler[1]), 0.0)
            if strag_rank != self.rank:
                # exposed wait on someone else; our own lateness is our
                # own negotiate time, not a stall
                stall_s = min(strag_wait, negotiate_s)
        phases = {
            "negotiate": negotiate_s - stall_s,
            "fuse_dispatch": max(dispatch_s - exec_s, 0.0),
            "device_exec": exec_s,
            "stall": stall_s,
            "host_overhead": max(wall_s - negotiate_s - dispatch_s, 0.0),
        }
        with self._lock:
            compile_s = self._compile_pending
            self._compile_pending = 0.0
        if compile_s > 0.0:
            # compile stalls happen inside the dispatch window; move the
            # compiled slice out of device_exec into host_overhead so a
            # recompile storm reads as host overhead, not device work
            shift = min(compile_s, phases["device_exec"])
            phases["device_exec"] -= shift
            phases["host_overhead"] += shift
        rec = {"ts": time.time(), "tensors": int(tensors),
               "wall_s": wall_s,
               "compile_s": round(compile_s, 6),
               "straggler_rank": strag_rank,
               "straggler_wait_s": round(strag_wait, 6)}
        for p in PHASES:
            rec[p + "_s"] = phases[p]
        rec.update(self._counter_deltas())
        with self._lock:
            self._ring.append(rec)
            self._total += 1
            self._sum_wall += wall_s
            self._sum_comm += negotiate_s  # negotiate phase + stall
            self._sum_exec += exec_s
            self._sum_wire += rec["wire_bytes"]
            sum_wall, sum_comm = self._sum_wall, self._sum_comm
            sum_exec, sum_wire = self._sum_exec, self._sum_wire
        self._m_steps.inc()
        self._m_step_s.observe(wall_s)
        for p in PHASES:
            self._m_phase[p].observe(phases[p])
        self._m_wire.observe(rec["wire_bytes"])
        if sum_wall > 0:
            self._m_exposed.set(sum_comm / sum_wall)
        if sum_exec > 0:
            self._m_gbps.set(sum_wire / sum_exec / 1e9)
        hits = self._last_counters.get("plan_hits", 0.0)
        misses = self._last_counters.get("plan_misses", 0.0)
        if hits + misses > 0:
            self._m_hit.set(hits / (hits + misses))
        return rec

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def records(self, last: Optional[int] = None) -> List[dict]:
        """The ring's contents, oldest first (``last`` keeps the newest N)."""
        with self._lock:
            recs = list(self._ring)
        if last is not None:
            recs = recs[-int(last):]
        return recs

    def records_since(self, cursor: int) -> Tuple[int, List[dict]]:
        """Records appended after position ``cursor`` (a value previously
        returned by this method; start from 0), plus the new cursor.
        Records evicted by ring wraparound in between are simply gone —
        the SLO engine evaluates what survived, it does not block."""
        with self._lock:
            total = self._total
            n_new = min(max(total - int(cursor), 0), len(self._ring))
            recs = list(self._ring)[len(self._ring) - n_new:] if n_new else []
        return total, recs

    def window_score(self, cursor: int) -> Tuple[int, Optional[float], dict]:
        """Goodput score over the records since ``cursor`` — the
        autotuner's objective (docs/autotune.md): effective allreduce
        bytes/sec discounted by the exposed-communication fraction,

            score = allreduce_gbps * 1e9 * (1 - exposed_comm_frac)

        so a config that moves bytes fast but leaves the step blocked on
        negotiation scores below one that overlaps. Returns
        ``(new_cursor, score, window_stats)``; score is None when the
        window holds no records or no wire/exec activity (idle windows
        must not be scored — the autotuner skips them rather than
        observing a fake zero)."""
        cursor, recs = self.records_since(cursor)
        if not recs:
            return cursor, None, {}
        st = self.stats(records=recs)
        gbps = st.get("allreduce_gbps", 0.0)
        if gbps <= 0.0:
            return cursor, None, st
        frac = min(max(st.get("exposed_comm_frac", 0.0), 0.0), 1.0)
        return cursor, gbps * 1e9 * (1.0 - frac), st

    def stats(self, records: Optional[List[dict]] = None) -> dict:
        """Flat derived-stat dict — the namespace SLO budgets bind to.

        Over the whole ring by default, or over an explicit window (the
        SLO engine passes the records since its last evaluation).
        ``negotiate_*`` stats cover the full negotiation round including
        any stall slice, matching what a training loop experiences.
        """
        recs = self.records() if records is None else records
        out = {"steps": len(recs)}
        if not recs:
            return out
        walls = sorted(r["wall_s"] for r in recs)
        rounds = sorted(r["negotiate_s"] + r["stall_s"] for r in recs)
        stalls = sorted(r["stall_s"] for r in recs)
        overheads = sorted(r["host_overhead_s"] for r in recs)
        sum_wall = sum(walls)
        sum_comm = sum(rounds)
        sum_exec = sum(r["device_exec_s"] for r in recs)
        sum_wire = sum(r["wire_bytes"] for r in recs)
        hits = sum(r["plan_hits"] for r in recs)
        misses = sum(r["plan_misses"] for r in recs)
        compiles = sorted(r.get("compile_s", 0.0) for r in recs)
        out.update({
            # compile attribution (utils/memledger.py): SLO budgets like
            # compile_seconds_p95<=0.5 bind here to bound recompile storms
            "compile_seconds_total": sum(compiles),
            "compile_seconds_p95": _percentile(compiles, 0.95),
            "step_p50_ms": _percentile(walls, 0.50) * 1e3,
            "step_p95_ms": _percentile(walls, 0.95) * 1e3,
            "negotiate_p50_ms": _percentile(rounds, 0.50) * 1e3,
            "negotiate_p95_ms": _percentile(rounds, 0.95) * 1e3,
            "stall_p95_ms": _percentile(stalls, 0.95) * 1e3,
            # per-step Python outside negotiation and dispatch — the
            # residual megaplan replay drives toward zero; SLO budgets
            # like host_overhead_p95_ms<=1 bind here
            "host_overhead_p50_ms": _percentile(overheads, 0.50) * 1e3,
            "host_overhead_p95_ms": _percentile(overheads, 0.95) * 1e3,
            "exposed_comm_frac": (sum_comm / sum_wall) if sum_wall else 0.0,
            # no plan activity in the window means nothing missed, not a
            # 0% hit rate — a >= budget must not breach on idle windows
            "plan_hit_rate": (hits / (hits + misses))
            if (hits + misses) else 1.0,
            "step_wire_bytes": sum_wire / len(recs),
            "allreduce_gbps": (sum_wire / sum_exec / 1e9)
            if sum_exec > 0 else 0.0,
        })
        # KV control-plane latency (hvd_kv_request_seconds exists only
        # with sharding/hierarchy on): lets SLO budgets like
        # kv_request_p95_ms<=50 catch a degrading rendezvous store. The
        # histogram is cumulative-process, not windowed — good enough
        # for a breach gate, and absent series add no field at all.
        from . import metrics as metrics_mod

        kv_p95 = metrics_mod.get_registry().histogram_quantile(
            "hvd_kv_request_seconds", 0.95)
        if kv_p95 is not None:
            out["kv_request_p95_ms"] = kv_p95 * 1e3
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in out.items()}

    def phase_summary(self, records: Optional[List[dict]] = None) -> dict:
        """Per-phase p50/p95/max (ms) and share of total recorded wall
        time — the step-decomposition view ``GET /perf`` shows per rank."""
        recs = self.records() if records is None else records
        if not recs:
            return {}
        sum_wall = sum(r["wall_s"] for r in recs) or 1.0
        out = {}
        for p in PHASES:
            vals = sorted(r[p + "_s"] for r in recs)
            out[p] = {"p50_ms": round(_percentile(vals, 0.50) * 1e3, 6),
                      "p95_ms": round(_percentile(vals, 0.95) * 1e3, 6),
                      "max_ms": round(vals[-1] * 1e3, 6),
                      "share": round(sum(vals) / sum_wall, 6)}
        return out

    def snapshot(self) -> dict:
        """Push payload for ``perf/rank{k}`` (kept compact: derived stats
        plus the newest few raw records, not the whole ring)."""
        recs = self.records()
        with self._lock:
            total = self._total
        return {"rank": self.rank, "steps": total,
                "stats": self.stats(records=recs),
                "phases": self.phase_summary(records=recs),
                "recent": recs[-5:]}

    def report(self) -> dict:
        """``hvd.perf_report()`` body for this rank."""
        out = self.snapshot()
        out["enabled"] = True
        out["capacity"] = self.capacity
        return out


# --------------------------------------------------------------------------
# SLO budget engine
# --------------------------------------------------------------------------

_OPS = ("<=", ">=")


def parse_slo_spec(text: str) -> List[Tuple[str, str, float]]:
    """Parse ``HOROVOD_SLO_SPEC`` into ``(stat_name, op, limit)`` budgets.

    Accepts the inline grammar (``negotiate_p95_ms<=5,plan_hit_rate>=0.95``),
    an inline JSON object mapping stat name to a bound string
    (``{"negotiate_p95_ms": "<=5"}``), or a path to a JSON file holding
    either form. Raises ``ValueError`` on anything malformed.
    """
    text = (text or "").strip()
    if not text:
        return []
    if not text.startswith("{") and os.path.isfile(text):
        with open(text, "r", encoding="utf-8") as f:
            content = f.read().strip()
        if not content:
            raise ValueError(f"SLO spec file {text!r} is empty")
        return parse_slo_spec(content)
    clauses: List[Tuple[str, str]] = []
    if text.startswith("{"):
        try:
            obj = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"SLO spec is not valid JSON: {e}") from e
        if not isinstance(obj, dict):
            raise ValueError("JSON SLO spec must be an object of "
                             "{stat_name: bound}")
        clauses = [(str(k), str(v)) for k, v in obj.items()]
    else:
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            for op in _OPS:
                if op in part:
                    name, _, bound = part.partition(op)
                    clauses.append((name.strip(), op + bound.strip()))
                    break
            else:
                raise ValueError(
                    f"SLO clause {part!r} has no comparator (use "
                    "name<=value or name>=value)")
    budgets: List[Tuple[str, str, float]] = []
    for name, bound in clauses:
        bound = bound.strip()
        op = bound[:2]
        if op not in _OPS or not name:
            raise ValueError(f"SLO bound {bound!r} for {name!r} must start "
                             "with <= or >=")
        try:
            limit = float(bound[2:])
        except ValueError as e:
            raise ValueError(
                f"SLO bound {bound!r} for {name!r}: not a number") from e
        budgets.append((name, op, limit))
    return budgets


class SloEngine:
    """Evaluates declared budgets over each new window of ledger records.

    Single-threaded by construction: ``evaluate()`` runs on the
    MetricsDumper thread (its flush cadence is the evaluation cadence).
    A budget fires once per breach window — it latches on the first
    breaching window and re-arms when a later window is back in bound —
    so a sustained breach produces one escalation, not one per flush.
    """

    def __init__(self, ledger: PerfLedger, budgets, stall_inspector=None):
        self.ledger = ledger
        self.budgets = list(budgets)
        self._stall = stall_inspector
        self._cursor = 0
        self._latched: set = set()
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._m_evals = reg.counter(
            "hvd_slo_evaluations_total",
            "SLO budget evaluation passes (one per flush with new steps)")
        self._m_breach = {
            name: reg.counter(
                "hvd_slo_breach_total",
                "SLO budget breach windows (fires once per window)",
                budget=name)
            for name, _, _ in self.budgets}

    def attach_stall_inspector(self, inspector) -> None:
        self._stall = inspector

    @staticmethod
    def _holds(value: float, op: str, limit: float) -> bool:
        return value <= limit if op == "<=" else value >= limit

    def _fire(self, name: str, op: str, limit: float, value: float) -> None:
        self._m_breach[name].inc()
        flightrec_mod.note("slo_breach", budget=name,
                           value=round(float(value), 6),
                           bound=f"{op}{limit:g}", rank=self.ledger.rank)
        detail = f"{value:.4g} vs bound {op}{limit:g}"
        inspector = self._stall
        if inspector is not None:
            inspector.note_slo_breach(name, detail)
        else:
            LOG.warning("SLO budget %r breached: %s.", name, detail)

    def evaluate(self) -> List[dict]:
        """One pass over the records since the last call; returns the
        budgets that newly fired (empty when no new records arrived)."""
        self._cursor, recs = self.ledger.records_since(self._cursor)
        if not recs:
            return []
        self._m_evals.inc()
        stats = self.ledger.stats(records=recs)
        fired: List[dict] = []
        for name, op, limit in self.budgets:
            value = stats.get(name)
            if value is None:
                continue
            if self._holds(float(value), op, limit):
                self._latched.discard(name)
            elif name not in self._latched:
                self._latched.add(name)
                self._fire(name, op, limit, float(value))
                fired.append({"budget": name, "bound": f"{op}{limit:g}",
                              "value": float(value)})
        return fired

    def state(self) -> dict:
        """JSON-able engine view for reports and ``GET /perf``."""
        return {"budgets": [
            {"budget": name, "bound": f"{op}{limit:g}",
             "breaching": name in self._latched}
            for name, op, limit in self.budgets]}


# --------------------------------------------------------------------------
# Process-global ledger + engine (the utils/tracing.py module-trio
# pattern): get_ledger() returns None when HOROVOD_PERFLEDGER is off, and
# every hook site costs exactly one is-None check in that state.
# --------------------------------------------------------------------------

_LEDGER: Optional[PerfLedger] = None
_ENGINE: Optional[SloEngine] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_PERFLEDGER)


def get_ledger() -> Optional[PerfLedger]:
    return _LEDGER


def get_engine() -> Optional[SloEngine]:
    return _ENGINE


def init_ledger(rank: int = 0, stall_inspector=None) -> Optional[PerfLedger]:
    """Create the process ledger when ``HOROVOD_PERFLEDGER`` is set
    (idempotent, like flightrec's init_recorder) and arm the SLO engine
    when ``HOROVOD_SLO_SPEC`` is also set; no-op returning None when off.
    A malformed spec is logged and skipped — a bad budget string must not
    take the job down at init."""
    global _LEDGER, _ENGINE
    if not enabled():
        return _LEDGER
    if _LEDGER is None:
        capacity = env_schema.get_int(env_schema.HOROVOD_PERFLEDGER_BUFFER,
                                      DEFAULT_CAPACITY)
        _LEDGER = PerfLedger(rank=rank, capacity=capacity)
    spec = env_schema.get_str(env_schema.HOROVOD_SLO_SPEC)
    if _ENGINE is None and spec.strip():
        try:
            budgets = parse_slo_spec(spec)
        except ValueError as e:
            budgets = []
            LOG.warning("ignoring malformed HOROVOD_SLO_SPEC: %s", e)
        if budgets:
            _ENGINE = SloEngine(_LEDGER, budgets,
                                stall_inspector=stall_inspector)
    if _ENGINE is not None and stall_inspector is not None:
        _ENGINE.attach_stall_inspector(stall_inspector)
    return _LEDGER


def reset_ledger() -> None:
    """Drop the process ledger and SLO engine (test/bench helper)."""
    global _LEDGER, _ENGINE, _STALL_WARNED
    _LEDGER = None
    _ENGINE = None
    _STALL_WARNED = False


def evaluate_slos() -> List[dict]:
    """Cold-path convenience for the MetricsDumper: run one SLO pass iff
    the engine is armed."""
    engine = _ENGINE
    if engine is None:
        return []
    return engine.evaluate()


# one-shot guard for the stall-attribution warning below; reset together
# with the ledger so tests observe the warning deterministically
_STALL_WARNED = False


def report() -> dict:
    """``hvd.perf_report()`` body: ``{"enabled": False}`` when the ledger
    is off, else this rank's stats/phase decomposition plus the SLO
    engine's budget states when one is armed.

    Straggler/stall attribution comes from coordinator verdicts that
    only exist when cross-rank tracing is on: without ``HOROVOD_TRACE``
    the ``stall`` phase reads 0 because no verdicts arrive, not because
    no rank stalled. The report marks that with
    ``stall_attributed: False`` (and warns once) instead of silently
    showing a clean decomposition."""
    global _STALL_WARNED
    ledger = _LEDGER
    if ledger is None:
        return {"enabled": False}
    out = ledger.report()
    from . import tracing as tracing_mod

    attributed = tracing_mod.get_tracer() is not None
    out["stall_attributed"] = attributed
    if not attributed and not _STALL_WARNED:
        _STALL_WARNED = True
        LOG.warning(
            "perf_report(): stall/straggler attribution needs "
            "HOROVOD_TRACE=1 — the stall phase reads 0 because "
            "coordinator straggler verdicts are unavailable, not because "
            "no rank stalled.")
    engine = _ENGINE
    if engine is not None:
        out["slo"] = engine.state()
    return out
