"""Unified metrics & telemetry registry: counters, gauges, histograms,
Prometheus text exposition, JSON snapshots.

The reference exposes runtime health only through the Chrome-trace timeline
(timeline.cc) and the stall inspector's log lines (stall_inspector.cc) —
there is no aggregate view a monitoring system can scrape, which is exactly
the blind spot that let a wedged backend hang for 120 s with nothing in the
runtime able to surface it (BENCH_r05.json post-mortem). This module is the
missing L3 observability layer, designed for the eager runtime's hot paths:

- **Dependency-free**: stdlib only (no prometheus_client; the container
  must not need new packages). The text format follows the Prometheus
  exposition spec (version 0.0.4) so any standard scraper parses it.
- **Thread-safe and cheap**: every update is O(1) int/float arithmetic
  under one shared registry lock (``Histogram.observe`` adds a ``bisect``
  over a fixed bucket table). Metric *instances* are resolved once — at
  runtime construction, not per event — so the cycle loop never allocates
  label strings (the acceptance bound: enqueue-path updates are dict/int
  ops only).
- **Two exposures**: ``GET /metrics`` on the rendezvous HTTP server
  (runner/http_server.py) renders the scrape; ``HOROVOD_METRICS_FILE``
  periodically dumps the JSON snapshot for post-mortem of wedged runs
  (``MetricsDumper``). Workers in a launched job additionally push their
  snapshots into the launcher's KV store so one scrape of the launcher
  returns every rank's series, labeled ``rank="k"``.

Python API (mirrored as ``hvd.metrics_snapshot()``)::

    from horovod_tpu.utils import metrics
    reg = metrics.get_registry()
    c = reg.counter("hvd_allreduce_bytes_total", "wire bytes", dtype="float32")
    c.inc(4096)
    snap = reg.snapshot()          # JSON-able structured dict
    text = reg.render_prometheus() # exposition format 0.0.4
"""

from __future__ import annotations

import bisect
import json
import logging
import os
import threading
import time
from typing import Optional

from . import lockcheck

LOG = logging.getLogger("horovod_tpu")

# Default bucket tables (upper bounds, seconds / bytes / tensor counts).
# Fixed at metric creation: observe() only bisects, never resizes.
LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
SIZE_BUCKETS_BYTES = (
    1 << 10, 1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22,
    1 << 24, 1 << 26, 1 << 28, 1 << 30)
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _fmt(v) -> str:
    """Prometheus sample-value formatting: integers bare, floats via %g."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    """Base: name + frozen labels + a reference to the registry lock."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, labels: dict, lock):
        self.name = name
        self.help = help_text
        self.labels = dict(labels)
        self._lock = lock


class Counter(_Metric):
    """Monotonic counter (reference semantics: bytes_processed-style
    tallies, but queryable)."""

    kind = "counter"

    def __init__(self, name, help_text, labels, lock):
        super().__init__(name, help_text, labels, lock)
        self._value = 0

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value (queue depth, tuned knobs, oldest pending age)."""

    kind = "gauge"

    def __init__(self, name, help_text, labels, lock):
        super().__init__(name, help_text, labels, lock)
        self._value = 0

    def set(self, value):
        with self._lock:
            self._value = value

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        with self._lock:
            self._value -= amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram (cycle time, per-op latency, fused sizes).

    Buckets are upper bounds; the implicit +Inf bucket is always present.
    ``observe`` is a bisect over the fixed bound table + three int/float
    adds — no allocation, no resizing.
    """

    kind = "histogram"

    def __init__(self, name, help_text, labels, lock,
                 buckets=LATENCY_BUCKETS_S):
        super().__init__(name, help_text, labels, lock)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def cumulative(self):
        """[(upper_bound, cumulative_count), ...] ending with ('+Inf', n)."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for b, c in zip(self.bounds, counts[:-1]):
            acc += c
            out.append((b, acc))
        out.append(("+Inf", acc + counts[-1]))
        return out


class MetricsRegistry:
    """Thread-safe named-metric table with get-or-create semantics.

    One lock is shared by the registry and every metric it owns: a single
    uncontended ``threading.Lock`` acquire per update is cheaper than
    per-metric locks and makes ``snapshot()`` a consistent cut.
    """

    def __init__(self):
        self._lock = lockcheck.make_lock("metrics.registry")
        # key: (name, sorted-label-items tuple) -> metric
        self._metrics: dict[tuple, _Metric] = {}  # guarded-by: _lock

    def _get_or_create(self, cls, name, help_text, labels, **kw):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help_text, labels, self._lock, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help_text: str = "", **labels) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "", **labels) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  buckets=LATENCY_BUCKETS_S, **labels) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets=buckets)

    def reset(self):
        """Zero every metric in place (instances stay valid — runtime
        objects cache them). Test/bench helper, not a production path."""
        with self._lock:
            for m in self._metrics.values():
                if isinstance(m, Histogram):
                    m._counts = [0] * (len(m.bounds) + 1)
                    m._sum = 0.0
                    m._count = 0
                else:
                    m._value = 0

    # -- snapshots ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured, JSON-able consistent cut of every series."""
        with self._lock:
            metrics = list(self._metrics.values())
        counters, gauges, hists = [], [], []
        for m in metrics:
            if isinstance(m, Histogram):
                hists.append({"name": m.name, "labels": m.labels,
                              "buckets": [[b, c] for b, c in m.cumulative()],
                              "sum": m.sum, "count": m.count})
            elif isinstance(m, Counter):
                counters.append({"name": m.name, "labels": m.labels,
                                 "value": m.value})
            else:
                gauges.append({"name": m.name, "labels": m.labels,
                               "value": m.value})
        return {"ts": time.time(), "counters": counters, "gauges": gauges,
                "histograms": hists}

    def counter_value(self, name: str) -> float:
        """Sum of a counter family across all label sets (bench helper)."""
        return self.counter_values((name,))[name]

    def counter_values(self, names) -> dict:
        """Per-family sums for several counter families in one pass — one
        lock acquire and one table scan however many families are asked
        for. The perf ledger reads five families per recorded step, so
        the batch form keeps that read O(table) instead of O(5·table)."""
        out = {n: 0.0 for n in names}
        with self._lock:
            for (n, _), m in self._metrics.items():
                if n in out and isinstance(m, Counter):
                    out[n] += m._value
        return out

    def gauge_value(self, name: str) -> Optional[float]:
        """Non-creating read of a gauge family (first label set wins;
        the families this serves — feature toggles and peaks — are
        single-set). None when absent, so a reader (the health engine)
        can sample a feature-gated gauge without registering it and
        breaking that feature's zero-series-when-off contract."""
        with self._lock:
            for (n, _), m in self._metrics.items():
                if n == name and isinstance(m, Gauge):
                    return float(m._value)
        return None

    def histogram_quantile(self, name: str, q: float) -> Optional[float]:
        """Bucket-interpolated quantile over a histogram family, merged
        across label sets (the PromQL ``histogram_quantile`` estimate:
        linear within the winning bucket, lower bound 0, upper bound the
        last finite edge). None when the family has no observations —
        callers (perf ledger SLO fields) skip absent series instead of
        reporting a fake 0."""
        with self._lock:
            hists = [m for (n, _), m in self._metrics.items()
                     if n == name and isinstance(m, Histogram)]
            if not hists:
                return None
            bounds = hists[0].bounds
            counts = [0] * (len(bounds) + 1)
            for m in hists:
                if m.bounds != bounds:
                    continue  # mixed bucket layouts merge meaninglessly
                for i, c in enumerate(m._counts):
                    counts[i] += c
        total = sum(counts)
        if total == 0:
            return None
        rank = max(0.0, min(1.0, float(q))) * total
        acc = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if acc + c >= rank:
                if i >= len(bounds):
                    return bounds[-1] if bounds else 0.0
                lo = bounds[i - 1] if i > 0 else 0.0
                frac = (rank - acc) / c
                return lo + (bounds[i] - lo) * frac
            acc += c
        return bounds[-1] if bounds else 0.0

    def render_prometheus(self) -> str:
        return render_snapshots([({}, self.snapshot())])

    def dump_json(self, path: str):
        """Atomic-ish JSON dump for post-mortem of wedged runs: write to a
        sibling temp file, then rename — a reader never sees a torn dump."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)


def render_snapshots(snapshots) -> str:
    """Render one exposition from structured snapshots.

    ``snapshots`` is ``[(extra_labels, snapshot_dict), ...]``; series of
    the same family from different snapshots (ranks) are grouped under one
    HELP/TYPE header, as the exposition format requires — the launcher's
    ``/metrics`` merges every pushed worker snapshot through this.
    """
    # family name -> (kind, [(labels, payload), ...]); insertion-ordered
    families: dict[str, tuple[str, list]] = {}

    def add(kind, entry, extra):
        labels = dict(entry.get("labels") or {})
        labels.update(extra)
        fam = families.setdefault(entry["name"], (kind, []))
        if fam[0] != kind:
            return  # conflicting kinds across ranks: keep the first
        fam[1].append((labels, entry))

    for extra, snap in snapshots:
        for c in snap.get("counters", ()):
            add("counter", c, extra)
        for g in snap.get("gauges", ()):
            add("gauge", g, extra)
        for h in snap.get("histograms", ()):
            add("histogram", h, extra)

    lines = []
    for name, (kind, series) in families.items():
        lines.append(f"# TYPE {name} {kind}")
        for labels, entry in series:
            if kind != "histogram":
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt(entry['value'])}")
                continue
            for b, c in entry["buckets"]:
                bl = dict(labels)
                bl["le"] = b if isinstance(b, str) else _fmt(b)
                lines.append(f"{name}_bucket{_label_str(bl)} {c}")
            lines.append(f"{name}_sum{_label_str(labels)} "
                         f"{_fmt(entry['sum'])}")
            lines.append(f"{name}_count{_label_str(labels)} "
                         f"{entry['count']}")
    return "\n".join(lines) + "\n" if lines else ""


# --------------------------------------------------------------------------
# Process-global default registry: one per process, shared by every
# subsystem, surviving init/shutdown cycles (counters are cumulative over
# the process lifetime, like any Prometheus target's).
# --------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()

# KV-store scope workers push snapshots under (key: "rank{k}"); the
# rendezvous server's /metrics reads the same scope back.
KV_SCOPE = "metrics"


def get_registry() -> MetricsRegistry:
    return _REGISTRY


class MetricsDumper:
    """Background publisher: periodic ``HOROVOD_METRICS_FILE`` JSON dumps
    and (in a launched job) snapshot pushes into the launcher's KV store
    under ``metrics/rank{k}``, so the launcher's ``GET /metrics`` shows
    every rank. Both are best-effort — telemetry must never take down the
    job it is observing.
    """

    KV_SCOPE = KV_SCOPE

    def __init__(self, registry: MetricsRegistry, file_path: str = "",
                 interval_s: float = 30.0, kv_client=None,
                 rank: int = 0):
        self.registry = registry
        self.file_path = file_path
        self.interval_s = max(float(interval_s), 0.5)
        self.kv_client = kv_client
        self.rank = rank
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # monotonic freshness stamp riding every push: the launcher's
        # merge endpoints annotate ranks whose stamps lag the newest
        # (a wedged rank's last snapshot must read as stale, not current)
        self._push_seq = 0

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-metrics")
        self._thread.start()

    def flush(self):
        """One synchronous dump+push (shutdown path and tests)."""
        if self.file_path:
            try:
                self.registry.dump_json(self.file_path)
            except OSError as e:
                LOG.warning("metrics file dump failed: %s", e)
        if self.kv_client is not None:
            try:
                # chaos hooks: a dropped push is absorbed here (telemetry
                # is best-effort by contract); a torn push is stored and
                # must be skipped by the /metrics merge on read
                from . import faults as faults_mod

                faults_mod.fault_point("metrics.push")
                # elastic-generation tag: the launcher's /metrics merge
                # drops snapshots older than the newest (epoch, gen) seen,
                # so ranks of a pre-resize generation stop reporting
                # frozen counters (render_snapshots ignores extra keys)
                from ..common import env as env_schema

                snap = self.registry.snapshot()
                snap["elastic_epoch"] = env_schema.get_int(
                    env_schema.HOROVOD_ELASTIC_EPOCH, 0)
                snap["elastic_gen"] = env_schema.get_int(
                    env_schema.HOROVOD_ELASTIC_GEN, 0)
                self._push_seq += 1
                snap["push_seq"] = self._push_seq
                snap["push_ts"] = time.time()
                snap["push_interval_s"] = self.interval_s
                payload = faults_mod.corrupt(
                    "metrics.push", json.dumps(snap).encode())
                self.kv_client.put(self.KV_SCOPE, f"rank{self.rank}",
                                   payload)
            except Exception as e:
                LOG.debug("metrics KV push failed: %s", e)
            # trace push rides the same cadence: the launcher's
            # GET /timeline merges one buffer per rank (last write wins;
            # spans carry stable (name, round) ids)
            try:
                from . import tracing as tracing_mod

                tracer = tracing_mod.get_tracer()
                if tracer is not None:
                    self.kv_client.put(
                        tracing_mod.KV_SCOPE, f"rank{self.rank}",
                        json.dumps(tracer.snapshot()).encode())
            except Exception as e:
                LOG.debug("trace KV push failed: %s", e)
        # perf-ledger push + SLO evaluation ride the same cadence: the
        # flush interval IS the budget-evaluation window, and the pushed
        # snapshots feed the launcher's GET /perf merge. Outside the
        # kv_client gate so file-only (and test) dumpers still evaluate.
        try:
            from . import perfledger as perfledger_mod

            ledger = perfledger_mod.get_ledger()
            if ledger is not None:
                perfledger_mod.evaluate_slos()
                if self.kv_client is not None:
                    psnap = ledger.snapshot()
                    psnap["push_seq"] = self._push_seq
                    psnap["push_ts"] = time.time()
                    psnap["push_interval_s"] = self.interval_s
                    self.kv_client.put(
                        perfledger_mod.KV_SCOPE, f"rank{self.rank}",
                        json.dumps(psnap).encode())
        except Exception as e:
            LOG.debug("perf KV push failed: %s", e)
        # memory-ledger sampling + push ride the same cadence: the flush
        # interval IS the interval-sample cadence, and the pushed
        # snapshots feed the launcher's GET /memory merge. Outside the
        # kv_client gate so file-only (and test) dumpers still sample.
        try:
            from . import memledger as memledger_mod

            mledger = memledger_mod.get_ledger()
            if mledger is not None:
                mledger.sample(event="interval")
                if self.kv_client is not None:
                    msnap = mledger.snapshot()
                    msnap["push_seq"] = self._push_seq
                    msnap["push_ts"] = time.time()
                    msnap["push_interval_s"] = self.interval_s
                    self.kv_client.put(
                        memledger_mod.KV_SCOPE, f"rank{self.rank}",
                        json.dumps(msnap).encode())
        except Exception as e:
            LOG.debug("memory KV push failed: %s", e)
        # step-anatomy push rides the same cadence; the pushed snapshots
        # feed the launcher's GET /anatomy merge (and the anatomy lanes
        # of GET /timeline)
        try:
            from . import anatomy as anatomy_mod

            profiler = anatomy_mod.get_profiler()
            if profiler is not None and self.kv_client is not None:
                asnap = profiler.snapshot()
                asnap["push_seq"] = self._push_seq
                asnap["push_ts"] = time.time()
                asnap["push_interval_s"] = self.interval_s
                self.kv_client.put(
                    anatomy_mod.KV_SCOPE, f"rank{self.rank}",
                    json.dumps(asnap).encode())
        except Exception as e:
            LOG.debug("anatomy KV push failed: %s", e)
        # async-checkpoint status push rides the same cadence; the pushed
        # snapshots feed the launcher's GET /checkpoint merge
        try:
            from . import async_ckpt as async_ckpt_mod

            ckpt = async_ckpt_mod.get_checkpointer()
            if ckpt is not None and self.kv_client is not None:
                csnap = ckpt.snapshot_status()
                csnap["push_seq"] = self._push_seq
                csnap["push_ts"] = time.time()
                csnap["push_interval_s"] = self.interval_s
                self.kv_client.put(
                    async_ckpt_mod.KV_SCOPE, f"rank{self.rank}",
                    json.dumps(csnap).encode())
        except Exception as e:
            LOG.debug("checkpoint KV push failed: %s", e)
        # fleet-health sampling + detection ride the same cadence: the
        # flush interval IS the history-sampling cadence, and the pushed
        # snapshots feed the launcher's GET /history and GET /health
        # merges. Sampling sits outside the kv_client gate so file-only
        # (and test) dumpers still detect; the fault point precedes the
        # sample so a "drop" skips the whole pass cleanly (no torn ring).
        try:
            from . import faults as faults_mod
            from . import health as health_mod

            heng = health_mod.get_engine()
            if heng is not None:
                faults_mod.fault_point("health.sample")
                heng.sample_and_detect()
                if self.kv_client is not None:
                    hsnap = heng.snapshot()
                    hsnap["push_seq"] = self._push_seq
                    hsnap["push_ts"] = time.time()
                    hsnap["push_interval_s"] = self.interval_s
                    payload = faults_mod.corrupt(
                        "health.sample", json.dumps(hsnap).encode())
                    self.kv_client.put(
                        health_mod.KV_SCOPE, f"rank{self.rank}", payload)
        except Exception as e:
            LOG.debug("health sample/push failed: %s", e)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.flush()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.flush()  # final dump: the post-mortem artifact
