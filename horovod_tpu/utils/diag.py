"""Postmortem diagnostics: wedge watchdog, bundle builder, crash hooks.

Everything here exists for one question: *what was every rank doing when
it stopped making progress?* The answer is a **diagnostic bundle** — a
JSON document with all-thread stacks (``sys._current_frames``), the
lockcheck held-lock/inversion report, a metrics snapshot, open tracing
spans, the flight recorder's last events (utils/flightrec.py), and
live-state probes contributed by the runtime (background-cycle beat age,
the coordinator's missing-rank gather state). Bundles are produced:

- by the **wedge watchdog** (``HOROVOD_WATCHDOG_SECS``): a daemon thread
  that fires when the background cycle loop or an in-flight negotiation
  stops beating for the threshold, bumping ``hvd_watchdog_fired_total``;
- on **SIGUSR1** (dump and continue) and **SIGTERM** (dump, then chain
  the previous handler / die) and on an uncaught exception
  (``sys.excepthook``) — plus a final atexit dump if the watchdog ever
  fired, so an externally killed wedged process still leaves evidence;
- on demand via ``hvd.diagnose()``.

Bundles land in ``HOROVOD_DIAG_DIR`` (default: the system temp dir) as
``hvd_diag.rank{r}.{reason}.json`` and, in a launched job, are pushed to
the launcher's KV store (scope ``diag/rank{k}``) so the rendezvous
server's auth-exempt ``GET /debug`` can merge them and *name the wedged
rank* (:func:`merge_bundles`). See docs/observability.md, "Debugging a
hung job".
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import logging
import os
import signal
import socket
import sys
import tempfile
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ..common import env as env_schema
from ..common import util as common_util
from . import flightrec, lockcheck

LOG = logging.getLogger("horovod_tpu")

#: KV-store scope watchdog/crash dumps are pushed under (key "rank{k}");
#: the rendezvous server's GET /debug reads the same scope back.
KV_SCOPE = "diag"


def watchdog_secs() -> float:
    return env_schema.get_float(env_schema.HOROVOD_WATCHDOG_SECS, 0.0)


def diag_dir() -> str:
    return env_schema.get_str(env_schema.HOROVOD_DIAG_DIR) \
        or tempfile.gettempdir()


def _rank() -> int:
    return env_schema.get_int(env_schema.HOROVOD_RANK, 0)


# --------------------------------------------------------------------------
# Live-state probes: subsystems register callables returning JSON-able
# dicts (BackgroundRuntime registers cycle state, the coordinator its
# gather state) so the bundle sees runtime internals without diag
# importing ops/ (no import cycles). Every probe is best-effort.
# --------------------------------------------------------------------------

_PROBES: Dict[str, Callable[[], dict]] = {}
_probes_lock = threading.Lock()


def register_probe(name: str, fn: Callable[[], dict]) -> None:
    with _probes_lock:
        _PROBES[name] = fn


def unregister_probe(name: str) -> None:
    with _probes_lock:
        _PROBES.pop(name, None)


def thread_stacks() -> List[dict]:
    """Every live thread's current stack, watchdog-safe: reads
    ``sys._current_frames()`` without stopping the world."""
    threads = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        t = threads.get(ident)
        out.append({
            "thread_id": ident,
            "name": t.name if t is not None else "?",
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": "".join(traceback.format_stack(frame)),
        })
    return out


# RESOURCE_EXHAUSTED-shaped exception markers. XLA surfaces a device OOM
# as XlaRuntimeError("RESOURCE_EXHAUSTED: ..."); host allocators say
# "out of memory"; Python itself raises MemoryError. Matched on the
# rendered exception so wrapper exception types don't hide the verdict.
_ALLOC_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                  "out of memory", "Out of memory", "OutOfMemory",
                  "failed to allocate", "Allocation failure")


def is_alloc_failure(exc) -> bool:
    """OOM/alloc-failure classifier for the dump-first excepthook path:
    allocation-shaped exceptions get an ``oom`` bundle whose memory
    section names a suspect component instead of a bare dead rank."""
    if isinstance(exc, MemoryError):
        return True
    try:
        text = f"{type(exc).__name__}: {exc}"
    except Exception:
        return False
    return any(marker in text for marker in _ALLOC_MARKERS)


def maybe_dump_alloc_failure(exc) -> str:
    """Classify + dump in one call, for code that catches its own
    exceptions (training loops, framework shims): writes an ``oom``
    bundle iff ``exc`` is allocation-shaped. Returns the bundle path
    ("" when not an alloc failure or the write failed)."""
    if not is_alloc_failure(exc):
        return ""
    return dump_bundle("oom")


def build_bundle(reason: str, last_events: int = 200,
                 stall: Optional[dict] = None) -> dict:
    """The local diagnostic bundle (``hvd.diagnose()`` returns this)."""
    from . import metrics as metrics_mod

    bundle = {
        "reason": reason,
        "rank": _rank(),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "time_unix": time.time(),
        "time_monotonic": time.monotonic(),
        "threads": thread_stacks(),
        "lockcheck": lockcheck.report(),
        "metrics": metrics_mod.get_registry().snapshot(),
    }
    if stall:
        bundle["stall"] = stall
    recorder = flightrec.get_recorder()
    bundle["flight_events"] = [] if recorder is None \
        else recorder.events(last=last_events)
    try:
        from . import tracing as tracing_mod

        tracer = tracing_mod.get_tracer()
        if tracer is not None:
            bundle["trace"] = {"open_spans": tracer.open_spans(),
                               "report": tracer.report()}
    except Exception as e:  # tracing must never block a dump
        bundle["trace"] = {"error": repr(e)}
    probes = {}
    with _probes_lock:
        items = list(_PROBES.items())
    for name, fn in items:
        try:
            probes[name] = fn()
        except Exception as e:
            probes[name] = {"error": repr(e)}
    bundle["probes"] = probes
    # OOM forensics: the memory ledger's view (recent samples, component
    # attribution, top live buffers, suspect; {"enabled": False} plus the
    # buffer table when the ledger is off) and what the plan cache held —
    # a wedge dump used to show stacks but not the cache contents
    try:
        from . import memledger as memledger_mod

        bundle["memory"] = memledger_mod.forensics()
    except Exception as e:
        bundle["memory"] = {"error": repr(e)}
    try:
        from ..ops import collectives as collectives_mod

        bundle["plan_cache"] = collectives_mod.plan_cache_table()
    except Exception as e:
        bundle["plan_cache"] = [{"error": repr(e)}]
    return bundle


# Launcher KV client for watchdog/crash pushes. A dedicated client (not
# the MetricsDumper's): pushes fire from the watchdog/signal context
# concurrently with the dumper's cadence, and the HTTP client's
# keep-alive socket is not shareable across threads.
_kv_client = None


def set_kv_client(client) -> None:
    global _kv_client
    _kv_client = client


def bundle_path(reason: str, rank: Optional[int] = None) -> str:
    if rank is None:
        rank = _rank()
    return os.path.join(diag_dir(), f"hvd_diag.rank{rank}.{reason}.json")


def dump_bundle(reason: str, push: bool = True,
                stall: Optional[dict] = None) -> str:
    """Build + write (atomically) + best-effort KV-push one bundle.

    Returns the file path ("" if the write failed). Never raises:
    diagnostics taking down the job they are diagnosing is the one
    unforgivable failure mode here.
    """
    try:
        bundle = build_bundle(reason, stall=stall)
    except Exception:
        LOG.exception("diag: bundle build failed")
        return ""
    path = bundle_path(reason, bundle["rank"])
    payload = json.dumps(bundle, default=repr).encode()
    try:
        common_util.atomic_write_bytes(path, payload)
    except Exception as e:
        LOG.warning("diag: bundle write to %s failed: %s", path, e)
        path = ""
    if push and _kv_client is not None:
        try:
            _kv_client.put(KV_SCOPE, f"rank{bundle['rank']}", payload)
        except Exception as e:
            LOG.debug("diag: bundle KV push failed: %s", e)
    flightrec.note("diag_dump", reason=reason, path=path)
    return path


# --------------------------------------------------------------------------
# Wedge watchdog
# --------------------------------------------------------------------------

class Watchdog(threading.Thread):
    """Daemon thread that dumps diagnostics when progress stops.

    The watched loop calls :meth:`beat` once per cycle; long blocking
    sections bracket themselves with :meth:`enter`/:meth:`exit_phase` so
    a fire can say *which* phase wedged (e.g. ``negotiate``). One fire
    per wedge: the fired latch clears on the next beat, so a 10-minute
    hang produces one bundle, not one per poll.
    """

    def __init__(self, threshold_s: float,
                 dump: Callable[..., str] = dump_bundle):
        super().__init__(daemon=True, name="hvd-watchdog")
        self.threshold_s = float(threshold_s)
        self._dump = dump
        self._stop_ev = threading.Event()
        self._lock = lockcheck.make_lock("diag.watchdog")
        self._last_beat = time.monotonic()  # guarded-by: _lock
        self._phase = ""
        self._phase_since = 0.0
        self._fired = False
        self.fired_count = 0
        self._metric = None  # lazy: zero hvd_watchdog_* series until a fire

    def beat(self) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._fired = False

    def enter(self, phase: str) -> None:
        with self._lock:
            self._phase = phase
            self._phase_since = time.monotonic()
            self._last_beat = self._phase_since
            self._fired = False  # reaching enter() IS progress: re-arm

    def exit_phase(self, phase: str) -> None:
        with self._lock:
            if self._phase == phase:
                self._phase = ""
            self._last_beat = time.monotonic()
            self._fired = False

    def state(self) -> dict:
        """Probe payload: the current stall phase and beat age."""
        with self._lock:
            return {"phase": self._phase,
                    "age_s": time.monotonic() - self._last_beat,
                    "threshold_s": self.threshold_s,
                    "fired_count": self.fired_count}

    def run(self) -> None:
        poll = max(min(self.threshold_s / 4.0, 1.0), 0.05)
        while not self._stop_ev.wait(poll):
            with self._lock:
                age = time.monotonic() - self._last_beat
                phase = self._phase
                fire = not self._fired and age >= self.threshold_s
                if fire:
                    self._fired = True
                    self.fired_count += 1
            if fire:
                self._fire(phase, age)

    def _fire(self, phase: str, age: float) -> None:
        if self._metric is None:
            from . import metrics as metrics_mod

            self._metric = metrics_mod.get_registry().counter(
                "hvd_watchdog_fired_total",
                "wedge-watchdog diagnostics dumps")
        self._metric.inc()
        flightrec.note("watchdog", phase=phase, age_s=round(age, 3))
        LOG.warning(
            "watchdog: no progress for %.1f s (threshold %.1f s, phase %r)"
            " — dumping diagnostics", age, self.threshold_s, phase or "idle")
        try:
            self._dump("watchdog", stall={"phase": phase,
                                          "age_s": round(age, 3)})
        except Exception:
            LOG.exception("watchdog: diagnostics dump failed")

    def stop(self) -> None:
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout=5)


_WATCHDOG: Optional[Watchdog] = None


def get_watchdog() -> Optional[Watchdog]:
    return _WATCHDOG


def init_watchdog(threshold_s: Optional[float] = None) -> Optional[Watchdog]:
    """Start the process watchdog when ``HOROVOD_WATCHDOG_SECS`` > 0
    (idempotent); returns None when disabled."""
    global _WATCHDOG
    if threshold_s is None:
        threshold_s = watchdog_secs()
    if threshold_s <= 0:
        return _WATCHDOG
    if _WATCHDOG is None:
        _WATCHDOG = Watchdog(threshold_s)
        _WATCHDOG.start()
    return _WATCHDOG


def reset_watchdog() -> None:
    global _WATCHDOG
    wd = _WATCHDOG
    _WATCHDOG = None
    if wd is not None:
        wd.stop()


# --------------------------------------------------------------------------
# Signal / crash / exit hooks
# --------------------------------------------------------------------------

_hooks_installed = False


def install_crash_hooks() -> None:
    """Wire the bundle dump to SIGUSR1 (dump, keep running), SIGTERM
    (dump, then the previous disposition — the job still dies), uncaught
    exceptions, and — iff the watchdog ever fired — process exit.

    Installed from ``hvd.init()`` AFTER the fatal-exit hook
    (common/context.py), so the excepthook chain runs dump-first, then
    the rank's print-and-``os._exit``. Idempotent; best-effort on
    platforms/threads where signal registration fails.
    """
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    try:
        faulthandler.enable()
    except Exception:  # no usable stderr (embedded interpreters)
        pass

    def _handler(signum, frame, chain_prev=None):
        name = signal.Signals(signum).name.lower()
        dump_bundle(name)
        if chain_prev is None:
            return  # SIGUSR1: observe and continue
        if callable(chain_prev):
            chain_prev(signum, frame)
        elif chain_prev != signal.SIG_IGN:
            # restore the default disposition and re-deliver, so the
            # process still dies of SIGTERM after leaving evidence
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    for signame, chains in (("SIGUSR1", False), ("SIGTERM", True)):
        sig = getattr(signal, signame, None)
        if sig is None:
            continue
        try:
            prev = signal.getsignal(sig)
            if chains:
                signal.signal(sig, lambda n, f, p=prev: _handler(n, f, p))
            else:
                signal.signal(sig, _handler)
        except (ValueError, OSError):  # non-main thread / exotic platform
            pass

    prev_hook = sys.excepthook

    def _excepthook(etype, value, tb):
        try:
            dump_bundle("oom" if is_alloc_failure(value) else "crash")
        except Exception:
            pass
        prev_hook(etype, value, tb)

    sys.excepthook = _excepthook

    def _atexit_dump():
        wd = _WATCHDOG
        if wd is not None and wd.fired_count > 0:
            # the run wedged at some point: leave a final-state bundle
            # even if something later unstuck it or an outer kill landed
            dump_bundle("exit", push=False)

    atexit.register(_atexit_dump)


def reset_crash_hooks_for_tests() -> None:
    """Allow a test subprocess to re-install hooks (NOT an uninstall)."""
    global _hooks_installed
    _hooks_installed = False


# --------------------------------------------------------------------------
# Cross-rank merge (rendezvous server's GET /debug)
# --------------------------------------------------------------------------

def merge_bundles(bundles: Dict[int, dict]) -> dict:
    """Merge per-rank bundles into one attribution view.

    Suspect naming, strongest signal first: (1) any rank whose bundle
    reason is ``oom`` — the rank that hit the allocation failure is the
    suspect by definition, attributed to its memory section's dominant
    component; (2) the union of ``missing_ranks`` from any coordinator
    gather probe — the ranks the coordinator was still waiting on are
    the wedge by definition; (3) otherwise the rank with the largest
    watchdog stall age.
    """
    ranks: Dict[str, dict] = {}
    missing: set = set()
    oom_ranks = []
    worst_age, worst_rank = -1.0, None
    for rank, b in sorted(bundles.items()):
        if not isinstance(b, dict):
            continue
        stall = b.get("stall") or {}
        probes = b.get("probes") or {}
        coord = probes.get("coordinator") or {}
        mem = b.get("memory") or {}
        info = {
            "reason": b.get("reason"),
            "hostname": b.get("hostname"),
            "time_unix": b.get("time_unix"),
            "stall": stall,
            "threads": len(b.get("threads") or ()),
            "flight_events": len(b.get("flight_events") or ()),
            "open_spans": (b.get("trace") or {}).get("open_spans"),
            "coordinator": coord or None,
            "memory_suspect": mem.get("suspect"),
            "peak_bytes": mem.get("peak_bytes"),
        }
        ranks[str(rank)] = info
        if b.get("reason") == "oom":
            oom_ranks.append((rank, mem.get("suspect")))
        for m in coord.get("missing_ranks") or ():
            try:
                missing.add(int(m))
            except (TypeError, ValueError):
                pass
        try:
            age = float(stall.get("age_s", -1.0))
        except (TypeError, ValueError):
            age = -1.0
        if age > worst_age:
            worst_age, worst_rank = age, rank
    if oom_ranks:
        component = next((c for _, c in oom_ranks if c), None)
        attribution = "allocation failure (oom bundle)"
        if component:
            attribution += f": dominant component {component}"
        return {"ranks": ranks, "suspects": [r for r, _ in oom_ranks],
                "attribution": attribution}
    if missing:
        return {"ranks": ranks, "suspects": sorted(missing),
                "attribution": "coordinator gather: ranks never submitted"}
    if worst_rank is not None and worst_age >= 0:
        return {"ranks": ranks, "suspects": [worst_rank],
                "attribution": "largest watchdog stall age"}
    return {"ranks": ranks, "suspects": [], "attribution": "none"}
