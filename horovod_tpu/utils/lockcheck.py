"""Runtime lock-order / lock-hold auditor (opt-in: ``HOROVOD_LOCKCHECK=1``).

The background runtime holds a dozen locks (queue drain, controller
rounds, staging ring, tracer ring, metrics registry); a lock-order
inversion between any two of them is a deadlock that only fires under
the right thread interleaving. The auditor makes the *order* observable
without needing the unlucky schedule: every audited acquisition adds
``held-lock -> new-lock`` edges to a global name-keyed graph, and a new
edge that closes a cycle is reported immediately — with both acquisition
stacks (the one that established the reverse path and the one closing
the cycle) — even though no deadlock actually occurred on this run.

Zero-cost contract: with ``HOROVOD_LOCKCHECK`` unset, :func:`make_lock`
returns a plain ``threading.Lock`` — no wrapper, no per-acquire check,
no ``hvd_lockcheck_*`` series. With it set, each acquire costs a
thread-local stack push plus (first time an edge is seen) a graph
update; stacks are only captured for *new* edges, so steady state is
cheap enough to run the whole test suite audited (tests/conftest.py).

Deliberate limits, documented rather than papered over:

- Edges are keyed by lock *name*, so two instances sharing a name would
  alias; same-name self-edges are therefore skipped (a per-key lock
  striped N ways is not an inversion with itself).
- Metrics are synced only at moments when the releasing thread holds no
  audited lock: the registry's own lock is audited, and touching it from
  inside ``on_acquired`` (while the just-acquired lock — possibly the
  registry lock itself — is still held) would deadlock.

See docs/development.md; the static side of the same contract is
tools/hvdlint's lock-discipline rule.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

from ..common import env as env_schema

LOG = logging.getLogger("horovod_tpu")

_STACK_LIMIT = 12  # frames kept per captured acquisition stack


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_LOCKCHECK)


def _hold_warn_s() -> float:
    return env_schema.get_float(env_schema.HOROVOD_LOCKCHECK_HOLD_MS,
                                500.0) / 1000.0


def _stack() -> str:
    # drop the two auditor-internal frames at the tail
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


class Auditor:
    """Acquisition-graph recorder shared by a set of audited locks.

    ``self._mu`` is a plain (unaudited) leaf lock: nothing is called
    while holding it, so it cannot participate in any cycle."""

    def __init__(self, hold_warn_s: Optional[float] = None):
        self._mu = threading.Lock()
        self.hold_warn_s = hold_warn_s if hold_warn_s is not None \
            else _hold_warn_s()
        # (held_name, new_name) -> acquisition stack when first observed
        self._edges: Dict[Tuple[str, str], str] = {}
        self._succ: Dict[str, Set[str]] = {}
        self._inversions: List[dict] = []
        self._long_holds: List[dict] = []
        self._tls = threading.local()
        # mutated under _mu; synced to hvd_lockcheck_* by _publish() at
        # lock-free moments only (see module docstring). Acquires are
        # counted per-thread (no _mu on the steady-state acquire path)
        # and folded in at publish time.
        self._acquires = 0
        self._pending = {"inversions": 0, "long_holds": 0}

    # -- per-thread held stack: list of [lock_id, name, t_acquired, count]

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def lock(self, name: str) -> "_AuditedLock":
        return _AuditedLock(self, name, threading.Lock())

    def rlock(self, name: str) -> "_AuditedLock":
        return _AuditedLock(self, name, threading.RLock())

    # -- acquisition bookkeeping ------------------------------------------

    def on_acquired(self, lock: "_AuditedLock") -> None:
        held = self._held()
        for entry in held:
            if entry[0] == id(lock):  # reentrant RLock acquire: no edges
                entry[3] += 1
                return
        new_edges = [(e[1], lock.name) for e in held
                     if e[1] != lock.name
                     and (e[1], lock.name) not in self._edges]
        if new_edges:
            stack = _stack()
            found = []
            with self._mu:
                for edge in new_edges:
                    inv = self._record_edge(edge, stack)
                    if inv is not None:
                        found.append(inv)
            for inv in found:  # log outside _mu: handlers take their own locks
                LOG.error(
                    "lock-order inversion: %s -> %s closes cycle %s\n"
                    "-- acquisition closing the cycle (thread %s):\n%s"
                    "-- first acquisition of the reverse edge %s -> %s:\n%s",
                    inv["cycle"][0], inv["cycle"][1],
                    " -> ".join(inv["path"] + [inv["path"][0]]),
                    inv["thread"], inv["stack"],
                    inv["path"][0], inv["path"][1], inv["prior_stack"])
        self._tls.acq = getattr(self._tls, "acq", 0) + 1
        held.append([id(lock), lock.name, time.monotonic(), 1])

    def _record_edge(self, edge: Tuple[str, str],
                     stack: str) -> Optional[dict]:
        """Insert ``held -> new`` (caller holds ``_mu``); a path from
        ``new`` back to ``held`` existing first means the global order is
        cyclic — returns the inversion record (with both stacks)."""
        if edge in self._edges:
            return None
        held_name, new_name = edge
        inv = None
        path = self._find_path(new_name, held_name)
        if path is not None:
            inv = {
                "cycle": [held_name, new_name],
                "path": path,
                "thread": threading.current_thread().name,
                "stack": stack,
                "prior_stack": self._edges.get((path[0], path[1]),
                                               "<unrecorded>"),
            }
            self._inversions.append(inv)
            self._pending["inversions"] += 1
        self._edges[edge] = stack
        self._succ.setdefault(held_name, set()).add(new_name)
        return inv

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Shortest existing path src -> ... -> dst in the edge graph."""
        if src == dst:
            return [src]
        seen = {src}
        frontier = [[src]]
        while frontier:
            nxt = []
            for path in frontier:
                for succ in self._succ.get(path[-1], ()):
                    if succ == dst:
                        return path + [succ]
                    if succ not in seen:
                        seen.add(succ)
                        nxt.append(path + [succ])
            frontier = nxt
        return None

    def on_releasing(self, lock: "_AuditedLock") -> Optional[float]:
        """Pop the per-thread entry; returns the acquire timestamp when
        this release drops the last reentrant hold, else None."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == id(lock):
                held[i][3] -= 1
                if held[i][3] <= 0:
                    t0 = held[i][2]
                    del held[i]
                    return t0
                return None
        return None  # release without recorded acquire (foreign thread)

    def on_released(self, lock: "_AuditedLock", t0: Optional[float]) -> None:
        if t0 is None:
            return
        held_s = time.monotonic() - t0
        if held_s > self.hold_warn_s:
            with self._mu:
                self._long_holds.append({
                    "lock": lock.name, "held_s": held_s,
                    "thread": threading.current_thread().name})
                self._pending["long_holds"] += 1
            LOG.warning("lock %s held %.3f s (> %.3f s threshold) by %s",
                        lock.name, held_s, self.hold_warn_s,
                        threading.current_thread().name)
        # sync metrics only at lock-free moments, and only when there is
        # something worth a registry round-trip (events, or a batch of
        # acquires) — the steady-state release path stays tls-only
        if not self._held():
            acq = getattr(self._tls, "acq", 0)
            if acq >= 256 or any(self._pending.values()):
                self._publish()

    # -- reporting --------------------------------------------------------

    def _publish(self) -> None:
        """Sync pending counts into hvd_lockcheck_* series. Only called
        when the current thread holds no audited lock (the registry lock
        is itself audited; see module docstring)."""
        acq = getattr(self._tls, "acq", 0)
        self._tls.acq = 0
        with self._mu:
            self._acquires += acq
            delta = dict(self._pending)
            for k in self._pending:
                self._pending[k] = 0
        if not acq and not any(delta.values()):
            return
        try:
            from . import metrics as metrics_mod

            reg = metrics_mod.get_registry()
            if acq:
                reg.counter("hvd_lockcheck_acquires_total",
                            "audited lock acquisitions").inc(acq)
            if delta["inversions"]:
                reg.counter("hvd_lockcheck_inversions_total",
                            "lock-order inversions detected"
                            ).inc(delta["inversions"])
            if delta["long_holds"]:
                reg.counter("hvd_lockcheck_long_holds_total",
                            "lock holds exceeding the warn threshold"
                            ).inc(delta["long_holds"])
        except Exception:  # pragma: no cover - registry import race
            pass

    def inversions(self) -> List[dict]:
        with self._mu:
            return list(self._inversions)

    def edges(self) -> List[Tuple[str, str]]:
        """Every (held, acquired) name pair observed so far, sorted.
        tests/test_hvdlint.py asserts these are a subset of the static
        lock-order graph built by tools/hvdlint's lock-order pass."""
        with self._mu:
            return sorted(self._edges)

    def long_holds(self) -> List[dict]:
        with self._mu:
            return list(self._long_holds)

    def report(self) -> dict:
        with self._mu:
            return {
                "enabled": True,
                "acquires": self._acquires,
                "edges": len(self._edges),
                "inversions": list(self._inversions),
                "long_holds": list(self._long_holds),
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._succ.clear()
            self._inversions.clear()
            self._long_holds.clear()
            self._acquires = 0
            for k in self._pending:
                self._pending[k] = 0


class _AuditedLock:
    """Lock/RLock wrapper reporting acquisitions to an :class:`Auditor`.

    The inner lock is acquired *before* bookkeeping (so audit state never
    describes a lock the thread does not yet hold) and released *after*
    the held-stack pop (so hold time covers the full critical section)."""

    __slots__ = ("_aud", "name", "_inner")

    def __init__(self, auditor: Auditor, name: str, inner):
        self._aud = auditor
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._aud.on_acquired(self)
        return ok

    def release(self) -> None:
        t0 = self._aud.on_releasing(self)
        self._inner.release()
        self._aud.on_released(self, t0)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


_GLOBAL: Optional[Auditor] = None
_GLOBAL_MU = threading.Lock()


def auditor() -> Auditor:
    """The process-global auditor backing :func:`make_lock`."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_MU:
            if _GLOBAL is None:
                _GLOBAL = Auditor()
    return _GLOBAL


def make_lock(name: str):
    """A lock for runtime shared state: plain ``threading.Lock`` when the
    auditor is off (the common case — zero wrapper, zero checks), an
    audited wrapper registered under ``name`` when ``HOROVOD_LOCKCHECK=1``.
    Names are dotted ``module.role`` strings; they key the order graph."""
    if not enabled():
        return threading.Lock()
    return auditor().lock(name)


def make_rlock(name: str):
    """RLock variant of :func:`make_lock` (reentrant acquires are counted,
    not edges)."""
    if not enabled():
        return threading.RLock()
    return auditor().rlock(name)


def inversions() -> List[dict]:
    """Inversions seen by the global auditor ([] when auditing is off)."""
    if _GLOBAL is None:
        return []
    return _GLOBAL.inversions()


def edges() -> List[Tuple[str, str]]:
    """(held, acquired) pairs seen by the global auditor ([] when off)."""
    if _GLOBAL is None:
        return []
    return _GLOBAL.edges()


def report() -> dict:
    if _GLOBAL is None:
        return {"enabled": False}
    return _GLOBAL.report()
