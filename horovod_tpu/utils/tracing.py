"""Cross-rank distributed tracing: collective lifecycle spans, clock
alignment, merged timeline, straggler attribution.

The per-rank timeline (utils/timeline.py, reference timeline.cc) answers
"what is *this rank* doing"; the questions that kill multi-chip runs —
which rank is the straggler, where did step N's 40 ms go — need the *same*
named collective correlated across every rank on a common clock. This
module is that layer, Dapper-style span propagation shaped to the eager
runtime's pipeline:

- **Span** — one submitted collective, trace id ``(tensor_name, round)``,
  with wall-clock phase timestamps: submit → queue drain → negotiation
  start/end → dispatch start/end → completion-token ready. Spans ride the
  TensorEntry through ``ops/queue.py``; every terminal path goes through
  ``BackgroundRuntime._finish`` so a span always finalizes (the chaos-test
  invariant: faults may fail a span, never leak it).
- **Ring buffer** — finalized spans are serialized into the same native
  C++ SPSC ring the timeline owns (``_native`` hvd_tl_*), with the
  ``queue.SimpleQueue`` fallback preserved; a bounded deque
  (``HOROVOD_TRACE_BUFFER``, default 4096 spans) holds the drained tail
  for reports and pushes.
- **Clock alignment** — NTP-style offset estimation against the
  rendezvous server's auth-exempt ``GET /clock``: a few round-trip
  probes at init, ``offset = server_t - (t0+t1)/2`` from the min-RTT
  probe, ``uncertainty = rtt/2``. Spans record raw local wall time; the
  offset is applied at merge (and carried in every pushed buffer), so a
  late-estimated offset never splits one rank's spans across two clocks.
  ``HOROVOD_TRACE_CLOCK_OFFSET`` overrides the estimate (tests; hosts
  with a trusted external sync).
- **Merged timeline** — workers push span buffers into the launcher's KV
  store (scope ``trace/rank{k}``, riding the MetricsDumper cadence); the
  rendezvous server's auth-exempt ``GET /timeline`` merges them into one
  Chrome-trace JSON (``chrome://tracing`` / Perfetto): pid = rank, one
  lane per phase, clock-aligned microsecond timestamps.
- **Straggler attribution** — workers stamp their (aligned) submit time
  into the negotiation payload; the rank-0 coordinator records per-rank
  first-submission times per tensor and, when a tensor goes ready,
  computes which rank submitted last and how long the fastest submitter
  waited. Exposed as ``hvd_straggler_wait_seconds`` /
  ``hvd_straggler_last_rank_total{rank=…}`` on the coordinator, stamped
  back onto every rank's spans via the round response, surfaced through
  ``hvd.trace_report()`` and the stall inspector's warnings.

Zero-cost contract: when ``HOROVOD_TRACE`` is unset, ``get_tracer()``
returns None, no Span is ever allocated, no ring exists, the negotiation
wire format is byte-identical to the untraced build (the SAME_AS_LAST
1-byte fast path is preserved), and the cycle loop's only cost is a
``is None`` check per call site — enforced by benchmarks/trace_overhead.py.

Caveat (documented in docs/timeline.md): straggler attribution compares
*aligned* submit times across ranks, so its resolution is bounded by the
per-rank clock-offset uncertainty; waits smaller than the summed
uncertainties of the two ranks involved are noise, not signal.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue as queue_mod
import threading
import time
from typing import Any, Optional

from ..common import env as env_schema
from . import lockcheck
from . import metrics as metrics_mod

LOG = logging.getLogger("horovod_tpu")

# KV-store scope workers push span buffers under (key: "rank{k}"); the
# rendezvous server's /timeline reads the same scope back.
KV_SCOPE = "trace"

# Buckets for straggler waits: sub-millisecond waits are clock noise,
# multi-second waits are real input-pipeline/compile skew.
STRAGGLER_BUCKETS_S = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0)

# Span phase-timestamp slots (indices into Span.t). Kept as one list of
# wall-clock floats, not attributes: the hot path stamps by index and the
# serialized record is one JSON array.
T_SUBMIT = 0        # enqueue() accepted the entry
T_DRAIN = 1         # run_cycle() drained it from the queue
T_NEG_START = 2     # first negotiation round that carried it
T_NEG_END = 3       # round response marked it ready
T_DISPATCH_START = 4  # chunk assignment done, program dispatch begins
T_DISPATCH_END = 5  # dispatch returned (async launch complete)
T_DONE = 6          # _finish(): handle marked done
N_PHASES = 7

# (lane name, start slot, end slot) for the merged Chrome trace: one tid
# per lane per rank, so Perfetto shows queue/negotiate/dispatch stacks
# under each rank's process row.
PHASE_LANES = (
    ("queue", T_SUBMIT, T_DRAIN),
    ("negotiate", T_NEG_START, T_NEG_END),
    ("fuse", T_NEG_END, T_DISPATCH_START),
    ("dispatch", T_DISPATCH_START, T_DISPATCH_END),
)
OP_LANE_TID = 0  # full-span lane ("op") is always tid 0


class Span:
    """One collective's lifecycle. Allocated only when tracing is on."""

    __slots__ = ("name", "op", "round", "t", "chunk_bytes", "chunk_tensors",
                 "straggler_rank", "straggler_wait_s", "error")

    def __init__(self, name: str, op: str, now: float):
        self.name = name
        self.op = op
        self.round = -1  # negotiation round; -1 = single-process (no round)
        self.t: list[Optional[float]] = [now] + [None] * (N_PHASES - 1)
        self.chunk_bytes = 0
        self.chunk_tensors = 0
        self.straggler_rank = -1
        self.straggler_wait_s = 0.0
        self.error = False

    def to_record(self) -> dict:
        """Compact JSON form (pushed buffers, ring traffic)."""
        return {"n": self.name, "o": self.op, "r": self.round, "t": self.t,
                "cb": self.chunk_bytes, "ct": self.chunk_tensors,
                "sr": self.straggler_rank,
                "sw": round(self.straggler_wait_s, 6),
                "e": 1 if self.error else 0}


class _RingBuffer:
    """Finalized-span transport: the native C++ SPSC ring when built
    (same hvd_tl_* core the timeline rides), else a SimpleQueue. The ring
    is single-producer/single-consumer; finish() runs almost always on
    the cycle thread but also on teardown and enqueue-rejection paths,
    and drain() on the dumper thread and report() callers — so both
    sides take a lock here (only paid when tracing is on)."""

    def __init__(self):
        self._native = None
        self._q: Optional[queue_mod.SimpleQueue] = None
        self._put_lock = lockcheck.make_lock("tracing.ring_put")
        from .._native import lib as _native_lib

        try:
            L = _native_lib()
        except Exception:
            L = None
        if L is not None:
            try:
                from .timeline import _NativeRing

                self._native = _NativeRing(L)
            except Exception:
                self._native = None
        if self._native is None:
            self._q = queue_mod.SimpleQueue()

    def put(self, rec: dict):
        if self._native is not None:
            with self._put_lock:
                self._native.put(rec)
        else:
            self._q.put(rec)

    def drain(self) -> list[dict]:
        out = []
        if self._native is not None:
            while True:
                lines = self._native.drain_lines()
                if not lines:
                    return out
                for ln in lines:
                    if not ln:
                        continue
                    try:
                        out.append(json.loads(ln))
                    except ValueError:
                        continue
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue_mod.Empty:
                return out


class Tracer:
    """Process-global span factory + buffer. One per process, created at
    init only when HOROVOD_TRACE is set (see ``init_tracer``)."""

    def __init__(self, rank: int = 0, buffer_limit: int = 4096,
                 clock_offset_s: float = 0.0,
                 clock_uncertainty_s: Optional[float] = None):
        self.rank = rank
        self.clock_offset_s = float(clock_offset_s)
        self.clock_uncertainty_s = clock_uncertainty_s
        self._ring = _RingBuffer()
        self._spans: collections.deque = collections.deque(
            maxlen=max(int(buffer_limit), 1))  # guarded-by: _drain_lock
        self._drain_lock = lockcheck.make_lock("tracing.drain")
        # begun/finished are plain ints bumped under the GIL: begin() runs
        # on caller threads, finish() on the cycle thread; an approximate
        # read is fine (open_spans is a diagnostic, not a sync primitive)
        self.begun = 0
        self.finished = 0
        reg = metrics_mod.get_registry()
        self._m_spans = reg.counter(
            "hvd_trace_spans_total", "collective spans finalized")
        self._m_errors = reg.counter(
            "hvd_trace_span_errors_total", "spans finalized with an error")
        self._m_dropped = reg.counter(
            "hvd_trace_spans_dropped_total",
            "finalized spans dropped by a full ring")

    # -- clock --------------------------------------------------------------
    def aligned_now(self) -> float:
        """Wall clock on the rendezvous coordinator's timebase — the value
        stamped into negotiation payloads so the coordinator compares
        submit times from different ranks on one clock."""
        return time.time() + self.clock_offset_s

    # -- span lifecycle ------------------------------------------------------
    def begin(self, name: str, op: str) -> Span:
        self.begun += 1
        return Span(name, op, time.time())

    def finish(self, span: Span, error: bool = False):
        """Terminal: stamp T_DONE, serialize into the ring. Called from
        every _finish path (success, negotiation error, stall shutdown,
        runtime teardown) so started spans never leak."""
        if error:
            span.error = True
        span.t[T_DONE] = time.time()
        self.finished += 1
        self._m_spans.inc()
        if span.error:
            self._m_errors.inc()
        try:
            self._ring.put(span.to_record())
        except Exception:
            self._m_dropped.inc()

    def open_spans(self) -> int:
        return self.begun - self.finished

    # -- buffer access -------------------------------------------------------
    def drain(self) -> None:
        """Move finalized spans from the ring into the bounded deque."""
        with self._drain_lock:
            for rec in self._ring.drain():
                self._spans.append(rec)

    def records(self) -> list[dict]:
        self.drain()
        # the copy must also hold the lock: a dumper-thread drain()
        # appending mid-iteration is a RuntimeError on a deque
        with self._drain_lock:
            return list(self._spans)

    def snapshot(self) -> dict:
        """Pushed-buffer form: rank identity + clock calibration + spans.
        The offset rides every push so the merge can align buffers even
        when ranks estimated their offsets at different times."""
        return {"rank": self.rank,
                "clock_offset_s": self.clock_offset_s,
                "clock_uncertainty_s": self.clock_uncertainty_s,
                "spans": self.records()}


# ---------------------------------------------------------------------------
# Clock-offset estimation (NTP-style, against GET /clock)
# ---------------------------------------------------------------------------

def estimate_clock_offset(addr: str, port: int, probes: int = 5,
                          timeout: float = 5.0) -> tuple[float, float]:
    """A few KV round-trip probes against the rendezvous server's
    auth-exempt ``GET /clock``; returns ``(offset_s, uncertainty_s)`` from
    the minimum-RTT probe (offset = server_t - midpoint(t0, t1),
    uncertainty = rtt / 2 — the server read can fall anywhere inside the
    round trip). Raises if every probe fails."""
    import urllib.request

    best: Optional[tuple[float, float]] = None
    last_err: Optional[Exception] = None
    for _ in range(max(int(probes), 1)):
        try:
            t0 = time.time()
            with urllib.request.urlopen(
                    f"http://{addr}:{int(port)}/clock",
                    timeout=timeout) as resp:
                server_t = float(json.loads(resp.read())["t"])
            t1 = time.time()
        except Exception as e:
            last_err = e
            continue
        rtt = t1 - t0
        offset = server_t - (t0 + t1) / 2.0
        if best is None or rtt < best[1]:
            best = (offset, rtt)
    if best is None:
        raise RuntimeError(f"clock-offset estimation failed: {last_err}")
    return best[0], best[1] / 2.0


# ---------------------------------------------------------------------------
# Process-global tracer
# ---------------------------------------------------------------------------

_TRACER: Optional[Tracer] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_TRACE)


def get_tracer() -> Optional[Tracer]:
    """The hot-path gate: None when tracing is off — call sites hold the
    result and guard with ``is not None`` (no env read per event)."""
    return _TRACER


def init_tracer(rank: int = 0, addr: Optional[str] = None,
                port: Optional[int] = None) -> Optional[Tracer]:
    """Create the process tracer iff HOROVOD_TRACE is set. When a
    rendezvous endpoint is given, estimate the clock offset against it;
    HOROVOD_TRACE_CLOCK_OFFSET overrides the estimate. Idempotent per
    process shape: re-init replaces the tracer (elastic reinit gets a
    fresh buffer; the metrics it feeds are process-lifetime)."""
    global _TRACER
    if not enabled():
        return _TRACER
    offset, uncertainty = 0.0, None
    override = os.environ.get(env_schema.HOROVOD_TRACE_CLOCK_OFFSET)
    if override is not None:
        try:
            offset = float(override)
            uncertainty = 0.0
        except ValueError:
            LOG.warning("invalid %s=%r ignored",
                        env_schema.HOROVOD_TRACE_CLOCK_OFFSET, override)
    elif addr and port:
        try:
            offset, uncertainty = estimate_clock_offset(addr, int(port))
        except Exception as e:
            # best-effort: an unaligned trace is still a trace
            LOG.warning("clock-offset estimation failed (%s); spans from "
                        "this rank merge unaligned", e)
    _TRACER = Tracer(
        rank=rank,
        buffer_limit=env_schema.get_int(env_schema.HOROVOD_TRACE_BUFFER,
                                        4096),
        clock_offset_s=offset, clock_uncertainty_s=uncertainty)
    LOG.info("tracing enabled: rank=%d clock_offset=%+.6fs uncertainty=%s",
             rank, offset,
             f"{uncertainty:.6f}s" if uncertainty is not None else "n/a")
    return _TRACER


def reset_tracer():
    """Drop the process tracer (tests / benchmarks only)."""
    global _TRACER
    _TRACER = None


# ---------------------------------------------------------------------------
# Merged Chrome trace + reports
# ---------------------------------------------------------------------------

def merge_chrome_trace(buffers: list[dict],
                       anatomy: Optional[list[dict]] = None) -> dict:
    """Merge per-rank span buffers (``Tracer.snapshot()`` dicts) into one
    Chrome trace-event object: pid = rank, tid 0 the full op span, one tid
    per phase lane, all timestamps shifted by the buffer's clock offset
    into the rendezvous coordinator's timebase (microseconds).

    ``anatomy`` optionally carries per-rank step-anatomy snapshots
    (``AnatomyProfiler.snapshot()`` dicts): their chunk entities render
    on one extra "anatomy" lane per rank (shifted by that rank's trace
    clock offset when a trace buffer supplied one) and the merged
    ``horovod`` block gains a per-rank ``critical_path`` summary."""
    events: list[dict] = []
    ranks_meta: dict[str, dict] = {}
    straggler_counts: dict[str, int] = {}
    total_wait = 0.0
    for buf in buffers:
        try:
            rank = int(buf["rank"])
            spans = buf.get("spans", [])
        except (KeyError, TypeError, ValueError):
            continue  # half-written push: skip, next scrape catches up
        offset = float(buf.get("clock_offset_s") or 0.0)
        ranks_meta[str(rank)] = {
            "clock_offset_s": offset,
            "clock_uncertainty_s": buf.get("clock_uncertainty_s"),
            "spans": len(spans)}
        events.append({"ph": "M", "pid": rank, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"rank {rank}"}})
        events.append({"ph": "M", "pid": rank, "tid": OP_LANE_TID,
                       "name": "thread_name", "args": {"name": "op"}})
        for i, (lane, _, _) in enumerate(PHASE_LANES):
            events.append({"ph": "M", "pid": rank, "tid": i + 1,
                           "name": "thread_name", "args": {"name": lane}})
        for rec in spans:
            t = rec.get("t")
            if not t or t[T_SUBMIT] is None:
                continue
            us = [(x + offset) * 1e6 if x is not None else None for x in t]
            args = {"op": rec.get("o"), "round": rec.get("r"),
                    "chunk_bytes": rec.get("cb"),
                    "chunk_tensors": rec.get("ct"),
                    "error": bool(rec.get("e"))}
            sr = rec.get("sr", -1)
            if sr is not None and sr >= 0:
                args["straggler_rank"] = sr
                args["straggler_wait_s"] = rec.get("sw", 0.0)
                straggler_counts[str(sr)] = \
                    straggler_counts.get(str(sr), 0) + 1
                total_wait += float(rec.get("sw") or 0.0)
            end = us[T_DONE] if us[T_DONE] is not None else us[T_SUBMIT]
            events.append({"ph": "X", "pid": rank, "tid": OP_LANE_TID,
                           "name": rec.get("n", "?"), "cat": "collective",
                           "ts": us[T_SUBMIT],
                           "dur": max(end - us[T_SUBMIT], 0.0),
                           "args": args})
            for i, (lane, s0, s1) in enumerate(PHASE_LANES):
                if us[s0] is None or us[s1] is None:
                    continue
                events.append({"ph": "X", "pid": rank, "tid": i + 1,
                               "name": f"{rec.get('n', '?')}:{lane}",
                               "cat": lane, "ts": us[s0],
                               "dur": max(us[s1] - us[s0], 0.0)})
    out = {"traceEvents": events, "displayTimeUnit": "ms",
           "horovod": {"ranks": ranks_meta,
                       "stragglers": {"last_rank_counts": straggler_counts,
                                      "total_wait_s": round(total_wait, 6)}}}
    if anatomy:
        offsets = {r: m.get("clock_offset_s") or 0.0
                   for r, m in ranks_meta.items()}
        anatomy_tid = len(PHASE_LANES) + 1
        critical: dict[str, dict] = {}
        for buf in anatomy:
            try:
                rank = int(buf["rank"])
            except (KeyError, TypeError, ValueError):
                continue  # half-written push: skip, next poll catches up
            offset = float(offsets.get(str(rank), 0.0))
            cp = buf.get("critical_path")
            if isinstance(cp, dict):
                critical[str(rank)] = cp
            lanes = buf.get("lanes") or []
            if lanes:
                events.append({"ph": "M", "pid": rank, "tid": anatomy_tid,
                               "name": "thread_name",
                               "args": {"name": "anatomy"}})
            for ent in lanes:
                try:
                    ts0 = float(ent["ts0"])
                    dur = float(ent.get("dur_s") or 0.0)
                except (KeyError, TypeError, ValueError):
                    continue
                events.append({"ph": "X", "pid": rank, "tid": anatomy_tid,
                               "name": str(ent.get("name", "?")),
                               "cat": "anatomy",
                               "ts": (ts0 + offset) * 1e6,
                               "dur": max(dur * 1e6, 0.0)})
        out["horovod"]["critical_path"] = critical
    return out


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def _phase_summary(records: list[dict], s0: int, s1: int) -> Optional[dict]:
    vals = sorted(
        rec["t"][s1] - rec["t"][s0] for rec in records
        if rec.get("t") and rec["t"][s0] is not None
        and rec["t"][s1] is not None)
    if not vals:
        return None
    return {"count": len(vals),
            "p50_ms": round(_percentile(vals, 0.50) * 1e3, 4),
            "p95_ms": round(_percentile(vals, 0.95) * 1e3, 4),
            "max_ms": round(vals[-1] * 1e3, 4)}


def report() -> dict:
    """``hvd.trace_report()``: per-phase latency percentiles + straggler
    attribution over the tracer's buffered spans. ``{"enabled": False}``
    when tracing is off."""
    tracer = get_tracer()
    if tracer is None:
        return {"enabled": False}
    records = tracer.records()
    phases = {}
    for lane, s0, s1 in PHASE_LANES + (("total", T_SUBMIT, T_DONE),):
        s = _phase_summary(records, s0, s1)
        if s is not None:
            phases[lane] = s
    waits = sorted(r.get("sw", 0.0) for r in records
                   if r.get("sr", -1) is not None and r.get("sr", -1) >= 0)
    last_counts: dict[str, int] = {}
    for r in records:
        sr = r.get("sr", -1)
        if sr is not None and sr >= 0:
            last_counts[str(sr)] = last_counts.get(str(sr), 0) + 1
    out = {"enabled": True, "rank": tracer.rank,
           "clock_offset_s": tracer.clock_offset_s,
           "clock_uncertainty_s": tracer.clock_uncertainty_s,
           "spans": len(records),
           "open_spans": tracer.open_spans(),
           "errors": sum(1 for r in records if r.get("e")),
           "phases": phases}
    if waits:
        out["straggler"] = {
            "attributed_spans": len(waits),
            "last_rank_counts": last_counts,
            "wait_p50_ms": round(_percentile(waits, 0.50) * 1e3, 4),
            "wait_p95_ms": round(_percentile(waits, 0.95) * 1e3, 4),
            "wait_max_ms": round(waits[-1] * 1e3, 4)}
    return out
