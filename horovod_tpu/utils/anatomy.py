"""Step-anatomy profiler: per-entity critical path + headroom estimates.

The perf ledger (utils/perfledger.py) decomposes each step into five
aggregate phases and one ``exposed_comm_frac``; that answers "how much
time goes to communication" but not "*which collective* bounds the
step" — the question ROADMAP items 2 (megaplan replay) and 3
(comm/compute overlap scheduler) both need answered before their
budgets can be set. This module is that measurement layer: a bounded
ring of per-step records in which every step is a list of *entities* —
each dispatched chunk (named after its head tensor), the negotiation
round (named after the tensors it carried), the residual host gap, and
any compile event — each with its own span and exposed-comm seconds.

Per entity, ``span_s`` is the host-blocking window measured around the
dispatch (or negotiation) call; chunk entities additionally carry the
staging-ring completion token (the leased ``is_ready()`` device array
threaded through ops/queue.py), and ``device_s`` is stamped when the
token first polls ready — a resolved-by upper bound with one-cycle
granularity, reported for device-occupancy context, never folded into
critical-path attribution.

On top of the ring, two Amdahl-style what-if numbers per step:

- ``overlap_headroom_s`` — seconds saved if every dispatched
  collective's host-blocking window were fully overlapped with compute
  (background-queue collectives are async by construction: their
  consumers block in ``synchronize()``, not at dispatch). This is the
  ceiling for the ROADMAP item 3 overlap scheduler.
- ``replay_headroom_s`` — seconds saved if negotiation and the host
  gap went to ~0 (what a megaplan replay of a stable fusion sequence
  eliminates). This is the ceiling for ROADMAP item 2.

Exposure: ``hvd.anatomy_report()``, lazy ``hvd_anatomy_*`` series, an
``anatomy/rank{k}`` KV push on the MetricsDumper cadence merged by the
launcher's ``GET /anatomy``, and per-entity lanes plus a
``horovod.critical_path`` summary in the ``GET /timeline`` merge.

Zero-cost contract (same as utils/perfledger.py, enforced by
benchmarks/anatomy_overhead.py): with ``HOROVOD_ANATOMY`` unset no
profiler exists, hot paths pay one ``is None`` check per hook, and no
``hvd_anatomy_*`` series is registered — metric handles are resolved
in ``AnatomyProfiler.__init__``, lazily at enable.
"""

from __future__ import annotations

import collections
import time
from typing import List, Optional, Sequence, Tuple

from ..common import env as env_schema
from . import lockcheck

#: KV scope the MetricsDumper pushes per-rank profiler snapshots under
#: (``anatomy/rank{k}``); the launcher's ``GET /anatomy`` merges it.
KV_SCOPE = "anatomy"

DEFAULT_CAPACITY = 512

#: Newest chunk entities carried in a snapshot as Perfetto lane events
#: (``GET /timeline`` renders them on a per-rank "anatomy" lane).
LANE_LIMIT = 200

#: Entity kinds a step decomposes into. ``chunk`` spans are dispatch
#: windows of fused/quantized/single-tensor plans; ``negotiate`` is the
#: controller round (carrying any coordinator-attributed stall slice);
#: ``host_gap`` is wall time outside both; ``compile`` is XLA compile
#: seconds handed over by the memledger; ``megaplan`` is a whole-step
#: replay's single chained dispatch (ops/megaplan.py) — the step had no
#: per-chunk dispatch windows to decompose into.
KINDS = ("chunk", "negotiate", "host_gap", "compile", "megaplan")


def _entity_name(names: Sequence[str], prefix: str = "") -> str:
    """Stable display name for a (possibly fused) group of tensors:
    the head tensor plus a ``+N`` rider count, e.g. ``grad_0+3``."""
    if not names:
        return prefix or "anon"
    head = str(names[0])
    if len(names) > 1:
        head = f"{head}+{len(names) - 1}"
    return f"{prefix}{head}"


class AnatomyProfiler:
    """Bounded ring of per-step entity timelines.

    ``note_chunk()`` and ``record_step()`` run on the background cycle
    thread (``_cycle_chunks`` is cycle-thread-only scratch, no lock);
    readers copy the ring under the lock. Completion tokens are polled
    lazily — on the next ``record_step()`` or snapshot — so the hot
    path never blocks on a device array.
    """

    def __init__(self, rank: int = 0, capacity: int = DEFAULT_CAPACITY):
        self.rank = rank
        self.capacity = max(int(capacity), 16)
        self._lock = lockcheck.make_lock("anatomy.ring")
        self._ring = collections.deque(maxlen=self.capacity)  # guarded-by: _lock
        self._total = 0  # guarded-by: _lock
        # compile seconds handed over by the memledger since the last
        # recorded step (same handover contract as the perf ledger)
        self._compile_pending = 0.0  # guarded-by: _lock
        # chunk entities noted by the dispatch hooks since the last
        # record_step(); cycle-thread-only scratch, flushed per step
        self._cycle_chunks: List[Tuple[dict, object, float]] = []
        # unresolved completion tokens: (entity, token, t0_perf_counter)
        self._outstanding: List[Tuple[dict, object, float]] = []  # guarded-by: _lock
        from . import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._m_steps = reg.counter(
            "hvd_anatomy_steps_total",
            "steps recorded by the step-anatomy profiler")
        self._m_entities = reg.counter(
            "hvd_anatomy_entities_total",
            "timeline entities (chunks/negotiate/host_gap/compile) recorded")
        self._m_exposed = reg.counter(
            "hvd_anatomy_exposed_seconds_total",
            "seconds of step wall time exposed to communication "
            "(negotiation rounds plus host-blocking dispatch windows)")
        self._m_overlap = reg.counter(
            "hvd_anatomy_overlap_headroom_seconds_total",
            "cumulative step seconds recoverable by fully overlapping "
            "dispatched collectives with compute (ROADMAP item 3 ceiling)")
        self._m_replay = reg.counter(
            "hvd_anatomy_replay_headroom_seconds_total",
            "cumulative step seconds recoverable by eliminating "
            "negotiation + host gap via plan replay (ROADMAP item 2 ceiling)")
        self._m_crit = reg.histogram(
            "hvd_anatomy_critical_span_seconds",
            "span of the per-step critical-path entity",
            buckets=metrics_mod.LATENCY_BUCKETS_S)

    # -- hot-path hooks (cycle thread) ---------------------------------

    def note_chunk(self, names: Sequence[str], nbytes: int, tensors: int,
                   dispatch_s: float, token=None,
                   t0_pc: Optional[float] = None) -> None:
        """One dispatched chunk: ``dispatch_s`` is the measured
        host-blocking execute window, ``token`` the leased completion
        device array (``is_ready()``-pollable) when the plan produced
        one. Called between ``record_step()``s on the cycle thread."""
        dispatch_s = max(float(dispatch_s), 0.0)
        ent = {"kind": "chunk", "name": _entity_name(names),
               "bytes": int(nbytes), "tensors": int(tensors),
               "span_s": dispatch_s, "exposed_s": dispatch_s,
               "device_done": token is None,
               "ts0": time.time() - dispatch_s}
        self._cycle_chunks.append(
            (ent, token, t0_pc if t0_pc is not None else time.perf_counter()))

    def note_megaplan(self, names: Sequence[str], nbytes: int,
                      tensors: int, dispatch_s: float, token=None,
                      t0_pc: Optional[float] = None) -> None:
        """One whole-step megaplan replay (ops/megaplan.py): the entire
        captured schedule rode a single chained dispatch, so the step
        contributes one ``megaplan`` entity instead of per-chunk spans —
        GET /timeline renders it as its own lane."""
        dispatch_s = max(float(dispatch_s), 0.0)
        ent = {"kind": "megaplan",
               "name": _entity_name(names, prefix="megaplan:"),
               "bytes": int(nbytes), "tensors": int(tensors),
               "span_s": dispatch_s, "exposed_s": dispatch_s,
               "device_done": token is None,
               "ts0": time.time() - dispatch_s}
        self._cycle_chunks.append(
            (ent, token, t0_pc if t0_pc is not None else time.perf_counter()))

    def note_compile(self, seconds: float) -> None:
        """Attribute one XLA compile's wall time to the next recorded
        step (called from the memledger's compile instrumentation)."""
        with self._lock:
            self._compile_pending += max(float(seconds), 0.0)

    def record_step(self, wall_s: float, negotiate_s: float = 0.0,
                    dispatch_s: float = 0.0, tensors: int = 0,
                    names: Sequence[str] = (),
                    straggler: Optional[Tuple[int, float]] = None) -> dict:
        """Close one step: fold the cycle's chunk entities plus the
        negotiation round, host gap and pending compile seconds into a
        record, derive critical path and headroom, and append it."""
        wall_s = max(float(wall_s), 0.0)
        negotiate_s = min(max(float(negotiate_s), 0.0), wall_s)
        dispatch_s = max(float(dispatch_s), 0.0)
        chunks = self._cycle_chunks
        self._cycle_chunks = []
        now = time.time()

        entities: List[dict] = [c[0] for c in chunks]
        stall_s = 0.0
        strag_rank: Optional[int] = None
        if straggler is not None:
            strag_rank = int(straggler[0])
            if strag_rank != self.rank:
                # exposed wait on someone else; own lateness is own
                # negotiate time (same convention as the perf ledger)
                stall_s = min(max(float(straggler[1]), 0.0), negotiate_s)
        ent_neg = {"kind": "negotiate",
                   "name": _entity_name(names, prefix="negotiate:"),
                   "span_s": negotiate_s, "exposed_s": negotiate_s,
                   "stall_s": round(stall_s, 6),
                   "straggler_rank": strag_rank,
                   "ts0": now - wall_s}
        entities.append(ent_neg)
        host_gap_s = max(wall_s - negotiate_s - dispatch_s, 0.0)
        if host_gap_s > 0.0:
            entities.append({"kind": "host_gap", "name": "host_gap",
                             "span_s": host_gap_s, "exposed_s": 0.0,
                             "ts0": now - host_gap_s})
        with self._lock:
            compile_s = self._compile_pending
            self._compile_pending = 0.0
        if compile_s > 0.0:
            entities.append({"kind": "compile", "name": "compile",
                             "span_s": compile_s, "exposed_s": 0.0,
                             "ts0": now - compile_s})

        chunk_span = sum(e["span_s"] for e in entities
                         if e["kind"] in ("chunk", "megaplan"))
        # every background-queue collective is overlappable: consumers
        # block in synchronize(), not at dispatch, so its host-blocking
        # window is pure headroom for an overlap scheduler
        overlap_headroom = min(chunk_span, wall_s)
        replay_headroom = min(negotiate_s + host_gap_s, wall_s)
        critical = max(entities, key=lambda e: e["span_s"])
        exposed_s = negotiate_s + chunk_span
        rec = {"ts": now, "wall_s": wall_s,
               "negotiate_s": round(negotiate_s, 6),
               "dispatch_s": round(dispatch_s, 6),
               "host_gap_s": round(host_gap_s, 6),
               "compile_s": round(compile_s, 6),
               "stall_s": round(stall_s, 6),
               "straggler_rank": strag_rank,
               "tensors": int(tensors),
               "exposed_s": round(exposed_s, 6),
               "overlap_headroom_s": round(overlap_headroom, 6),
               "replay_headroom_s": round(replay_headroom, 6),
               "critical": critical["name"],
               "critical_kind": critical["kind"],
               "critical_span_s": round(critical["span_s"], 6),
               "entities": entities}
        with self._lock:
            self._ring.append(rec)
            self._total += 1
            for ent, token, t0 in chunks:
                if token is not None:
                    self._outstanding.append((ent, token, t0))
            self._outstanding = self._poll_tokens(self._outstanding)
        self._m_steps.inc()
        self._m_entities.inc(len(entities))
        self._m_exposed.inc(exposed_s)
        self._m_overlap.inc(overlap_headroom)
        self._m_replay.inc(replay_headroom)
        self._m_crit.observe(critical["span_s"])
        return rec

    # -- token resolution ----------------------------------------------

    def _poll_tokens(self, outstanding):
        """Resolve completion tokens that have become ready; returns the
        entries still pending (caller holds ``_lock`` and reassigns
        ``_outstanding``). ``device_s`` is the dispatch-start → poll
        interval: an upper bound on device completion with one-cycle
        granularity (documented as such, never used for attribution)."""
        if not outstanding:
            return outstanding
        now_pc = time.perf_counter()
        still: List[Tuple[dict, object, float]] = []
        for ent, token, t0 in outstanding:
            try:
                ready = bool(token.is_ready())
            except Exception:
                ready = True  # deleted/donated buffer: nothing left to wait on
            if ready:
                ent["device_done"] = True
                ent["device_s"] = round(max(now_pc - t0, 0.0), 6)
            else:
                still.append((ent, token, t0))
        # bound the unresolved set: a wedged device must not grow a list
        del still[:max(len(still) - self.capacity, 0)]
        return still

    # -- readers --------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def records(self, last: Optional[int] = None) -> List[dict]:
        """Ring contents, oldest first (``last`` keeps the newest N)."""
        with self._lock:
            self._outstanding = self._poll_tokens(self._outstanding)
            recs = list(self._ring)
        if last is not None:
            recs = recs[-int(last):]
        return recs

    def entity_table(self, records: Optional[List[dict]] = None) -> dict:
        """Per-entity aggregate: name -> {kind, count, span_s,
        exposed_s, critical_steps} over the ring (or a window)."""
        recs = self.records() if records is None else records
        table: dict = {}
        for rec in recs:
            for ent in rec["entities"]:
                row = table.setdefault(
                    ent["name"], {"kind": ent["kind"], "count": 0,
                                  "span_s": 0.0, "exposed_s": 0.0,
                                  "critical_steps": 0})
                row["count"] += 1
                row["span_s"] += ent["span_s"]
                row["exposed_s"] += ent.get("exposed_s", 0.0)
            table[rec["critical"]]["critical_steps"] += 1
        for row in table.values():
            row["span_s"] = round(row["span_s"], 6)
            row["exposed_s"] = round(row["exposed_s"], 6)
        return table

    def critical_path(self, records: Optional[List[dict]] = None) -> dict:
        """Which entity bounds the most steps (tie broken by total
        span): the one-line answer ``GET /timeline`` surfaces."""
        recs = self.records() if records is None else records
        if not recs:
            return {"top_entity": None, "kind": None, "critical_steps": 0,
                    "steps": 0, "share": 0.0}
        table = self.entity_table(records=recs)
        name, row = max(table.items(),
                        key=lambda kv: (kv[1]["critical_steps"],
                                        kv[1]["span_s"]))
        return {"top_entity": name, "kind": row["kind"],
                "critical_steps": row["critical_steps"],
                "steps": len(recs),
                "share": round(row["critical_steps"] / len(recs), 6)}

    def headroom(self, records: Optional[List[dict]] = None) -> dict:
        """Amdahl-style what-if numbers over the ring: mean per-step and
        cumulative seconds recoverable by (a) fully overlapping
        dispatched collectives and (b) replaying plans to eliminate
        negotiation + host gap."""
        recs = self.records() if records is None else records
        if not recs:
            return {"overlap_headroom_s": 0.0, "replay_headroom_s": 0.0,
                    "overlap_headroom_total_s": 0.0,
                    "replay_headroom_total_s": 0.0, "steps": 0}
        ov = sum(r["overlap_headroom_s"] for r in recs)
        rp = sum(r["replay_headroom_s"] for r in recs)
        return {"overlap_headroom_s": round(ov / len(recs), 6),
                "replay_headroom_s": round(rp / len(recs), 6),
                "overlap_headroom_total_s": round(ov, 6),
                "replay_headroom_total_s": round(rp, 6),
                "steps": len(recs)}

    def lanes(self, records: Optional[List[dict]] = None) -> List[dict]:
        """Newest chunk entities as Perfetto-lane events for the
        ``GET /timeline`` merge: {name, ts0 (epoch s), dur_s, kind}."""
        recs = self.records() if records is None else records
        out: List[dict] = []
        for rec in recs:
            for ent in rec["entities"]:
                if ent["kind"] not in ("chunk", "megaplan"):
                    continue
                out.append({"name": ent["name"], "ts0": ent["ts0"],
                            "dur_s": ent["span_s"], "kind": ent["kind"]})
        return out[-LANE_LIMIT:]

    def snapshot(self) -> dict:
        """Push payload for ``anatomy/rank{k}`` (compact: aggregates,
        the newest few records with trimmed entity lists, and the lane
        events — not the whole ring)."""
        recs = self.records()
        with self._lock:
            total = self._total
            inflight = len(self._outstanding)
        recent = []
        for rec in recs[-5:]:
            slim = dict(rec)
            slim["entities"] = sorted(
                rec["entities"], key=lambda e: e["span_s"], reverse=True)[:8]
            recent.append(slim)
        return {"rank": self.rank, "steps": total,
                "inflight_tokens": inflight,
                "entities": self.entity_table(records=recs),
                "critical_path": self.critical_path(records=recs),
                "headroom": self.headroom(records=recs),
                "recent": recent,
                "lanes": self.lanes(records=recs)}

    def report(self) -> dict:
        """``hvd.anatomy_report()`` body for this rank."""
        out = self.snapshot()
        out["enabled"] = True
        out["capacity"] = self.capacity
        return out


# --------------------------------------------------------------------------
# Process-global profiler (the utils/perfledger.py module-trio pattern):
# get_profiler() returns None when HOROVOD_ANATOMY is off, and every hook
# site costs exactly one is-None check in that state.
# --------------------------------------------------------------------------

_PROFILER: Optional[AnatomyProfiler] = None


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_ANATOMY)


def get_profiler() -> Optional[AnatomyProfiler]:
    return _PROFILER


def init_profiler(rank: int = 0) -> Optional[AnatomyProfiler]:
    """Create the process profiler when ``HOROVOD_ANATOMY`` is set
    (idempotent); no-op returning None when off."""
    global _PROFILER
    if not enabled():
        return _PROFILER
    if _PROFILER is None:
        capacity = env_schema.get_int(env_schema.HOROVOD_ANATOMY_BUFFER,
                                      DEFAULT_CAPACITY)
        _PROFILER = AnatomyProfiler(rank=rank, capacity=capacity)
    return _PROFILER


def reset_profiler() -> None:
    """Drop the process profiler (test/bench helper)."""
    global _PROFILER
    _PROFILER = None


def report() -> dict:
    """``hvd.anatomy_report()`` body: ``{"enabled": False}`` when the
    profiler is off, else this rank's entity table, critical path and
    headroom estimates."""
    profiler = _PROFILER
    if profiler is None:
        return {"enabled": False}
    return profiler.report()
