"""Persistent XLA compilation cache helper.

On tunneled/remote TPU platforms, compiles are RPCs to a service whose
availability can flap; a persistent cache makes every successfully
compiled program a one-time cost for the machine rather than per
process. (The reference has no analogue — CUDA kernels ship prebuilt;
for XLA the compile IS the build step, so cache management belongs in
the framework.)

The enabled cache directory is recorded (:func:`active_cache_dir`) so
the memledger's compile instrumentation (utils/memledger.py) can infer
persistent-cache hit/miss from the cache-dir entry delta across a
compile, and a failure to enable is visible three ways instead of being
a mystery recompile per process: a one-time warning with the reason,
the reason as the return value, and an ``hvd_compile_cache_enabled``
gauge (1/0).
"""

import logging
import os
from typing import Optional

from ..common import env as env_schema

LOG = logging.getLogger("horovod_tpu")

_ACTIVE_DIR: Optional[str] = None
_WARNED = False


def active_cache_dir() -> Optional[str]:
    """The persistent-cache directory enabled in this process, or None —
    the memledger's hit/miss inference keys off this."""
    return _ACTIVE_DIR


def enable_compilation_cache(cache_dir: Optional[str] = None,
                             min_compile_time_secs: float = 1.0,
                             ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: ``$HOROVOD_COMPILE_CACHE`` or ``~/.cache/horovod_tpu_xla``).

    Returns None on success, else the failure reason (also warned once
    per process and published on the ``hvd_compile_cache_enabled``
    gauge). Never raises: the cache is an optimization — but a
    mis-pointed ``HOROVOD_COMPILE_CACHE`` must be visible, not silent.
    """
    global _ACTIVE_DIR, _WARNED
    import jax

    try:
        cache_dir = (cache_dir
                     or os.environ.get(env_schema.HOROVOD_COMPILE_CACHE)
                     or os.path.join(os.path.expanduser("~"), ".cache",
                                     "horovod_tpu_xla"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        reason = None
    except Exception as e:
        reason = f"{type(e).__name__}: {e}"
    from . import metrics as metrics_mod

    metrics_mod.get_registry().gauge(
        "hvd_compile_cache_enabled",
        "1 when the persistent XLA compile cache is enabled, 0 when the "
        "last enable attempt failed").set(0 if reason else 1)
    if reason is None:
        _ACTIVE_DIR = cache_dir
        return None
    if not _WARNED:
        _WARNED = True
        LOG.warning("persistent compilation cache NOT enabled (%s): every "
                    "process pays every compile", reason)
    return reason
