"""Persistent XLA compilation cache helper.

On tunneled/remote TPU platforms, compiles are RPCs to a service whose
availability can flap; a persistent cache makes every successfully
compiled program a one-time cost for the machine rather than per
process. (The reference has no analogue — CUDA kernels ship prebuilt;
for XLA the compile IS the build step, so cache management belongs in
the framework.)
"""

import os
from typing import Optional

from ..common import env as env_schema


def enable_compilation_cache(cache_dir: Optional[str] = None,
                             min_compile_time_secs: float = 1.0) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: ``$HOROVOD_COMPILE_CACHE`` or ``~/.cache/horovod_tpu_xla``).
    Returns True if enabled. Never raises: the cache is an optimization.
    """
    import jax

    try:
        cache_dir = (cache_dir
                     or os.environ.get(env_schema.HOROVOD_COMPILE_CACHE)
                     or os.path.join(os.path.expanduser("~"), ".cache",
                                     "horovod_tpu_xla"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    except Exception:
        return False
