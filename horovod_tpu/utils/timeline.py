"""Chrome-tracing timeline writer.

Reference: /root/reference/horovod/common/timeline.{h,cc} — a dedicated
writer thread fed by a lock-free SPSC queue (timeline.h:84-100), emitting
Chrome trace-event JSON with a per-tensor NEGOTIATING → TOP_LEVEL → ACTIVITY
state machine, runtime start/stop (operations.cc:738-764), and optional
cycle markers.

Here: a daemon writer thread fed by the native C++ SPSC ring
(`horovod_tpu._native` hvd_tl_*, the direct analogue of the reference's
boost::lockfree::spsc_queue) with a ``queue.SimpleQueue`` fallback when
the native core isn't built; same JSON schema, so the output opens in
``chrome://tracing`` / Perfetto exactly like the reference's. Device-side
timing on TPU comes from ``jax.profiler`` traces instead of CUDA events —
`start_jax_profiler`/`stop_jax_profiler` bridge to XPlane dumps.
"""

from __future__ import annotations

import ctypes
import json
import os
import queue
import threading
import time
from typing import Optional

_RING_CAPACITY = 1 << 16  # events (reference: 1M; sized for host traces)
_DRAIN_BUF = 1 << 20


class _NativeRing:
    """ctypes wrapper over the C++ SPSC ring (core.cc hvd_tl_*)."""

    def __init__(self, lib):
        self._lib = lib
        self._ring = lib.hvd_tl_create(_RING_CAPACITY)
        self._buf = ctypes.create_string_buffer(_DRAIN_BUF)

    def put(self, rec):
        data = b"" if rec is None else json.dumps(rec).encode()
        self._lib.hvd_tl_push(self._ring, data, len(data))

    def drain_lines(self):
        n = self._lib.hvd_tl_drain(self._ring, self._buf, _DRAIN_BUF)
        if n <= 0:
            return []
        return self._buf.raw[:n].decode().splitlines()

    def __del__(self):
        try:
            self._lib.hvd_tl_destroy(self._ring)
        except Exception:
            pass


class Timeline:
    """Per-tensor lane trace writer (chrome trace-event format)."""

    def __init__(self, filename: str = "", mark_cycles: bool = False):
        self._native = None
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._file = None
        self._thread: Optional[threading.Thread] = None
        self._tids: dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.mark_cycles = mark_cycles
        self._start_ts = time.perf_counter()
        if filename:
            self._open(filename)

    # -- lifecycle ----------------------------------------------------------
    def _open(self, filename: str):
        # native ring load/build is deferred to here: most inits never
        # enable the timeline, and lib() may invoke a g++ build
        if self._native is None:
            from .._native import lib as _native_lib

            L = _native_lib()
            if L is not None:
                try:
                    self._native = _NativeRing(L)
                except Exception:
                    self._native = None
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._stop.clear()
        self._thread = threading.Thread(target=self._writer, daemon=True,
                                        name="hvd-timeline")
        self._thread.start()

    def reopen(self, filename: str, mark_cycles: bool = False):
        """Runtime start/stop (reference operations.cc:738-764)."""
        self.close()
        self.mark_cycles = mark_cycles
        if filename:
            self._open(filename)

    def close(self):
        if self._thread is not None:
            self._stop.set()
            self._q.put(None)
            self._thread.join(timeout=5)
            self._thread = None
        if self._file is not None:
            self._file.write("{}]\n")
            self._file.close()
            self._file = None

    @property
    def enabled(self) -> bool:
        return self._file is not None

    # -- event emission -----------------------------------------------------
    def _ts_us(self) -> float:
        return (time.perf_counter() - self._start_ts) * 1e6

    def _put(self, rec):
        if self._native is not None:
            self._native.put(rec)
        else:
            self._q.put(rec)

    def _tid(self, name: str) -> int:
        with self._lock:
            if name not in self._tids:
                self._tids[name] = len(self._tids) + 1
                self._put({"name": "process_name", "ph": "M", "pid": 0,
                           "tid": self._tids[name],
                           "args": {"name": name}})
            return self._tids[name]

    def _emit(self, name: str, ph: str, event: str, args=None):
        if not self.enabled:
            return
        rec = {"ph": ph, "ts": self._ts_us(), "pid": 0, "tid": self._tid(name)}
        if event:
            rec["name"] = event
        if args:
            rec["args"] = args
        self._put(rec)

    def negotiate_start(self, name: str, op_name: str):
        self._emit(name, "B", "NEGOTIATE_" + op_name)

    def negotiate_end(self, name: str):
        self._emit(name, "E", "")

    def start_activity(self, name: str, activity: str):
        self._emit(name, "B", activity)

    def end_activity(self, name: str):
        self._emit(name, "E", "")

    def mark_cycle_start(self):
        if self.enabled and self.mark_cycles:
            self._put({"ph": "i", "ts": self._ts_us(), "pid": 0, "tid": 0,
                       "name": "CYCLE_START", "s": "g"})

    # -- writer thread ------------------------------------------------------
    def _writer(self):
        if self._native is not None:
            while True:
                lines = self._native.drain_lines()
                for ln in lines:
                    if ln and self._file:
                        self._file.write(ln + ",\n")
                if lines and self._file:
                    self._file.flush()
                if self._stop.is_set() and not lines:
                    return
                if not lines:
                    time.sleep(0.02)
            return
        while True:
            try:
                rec = self._q.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if rec is None:
                # drain remaining
                while True:
                    try:
                        r = self._q.get_nowait()
                    except queue.Empty:
                        return
                    if r is not None and self._file:
                        self._file.write(json.dumps(r) + ",\n")
                return
            if self._file:
                self._file.write(json.dumps(rec) + ",\n")
                self._file.flush()


def start_jax_profiler(logdir: str):
    """Device-side profiling bridge: XPlane/perfetto dump via jax.profiler
    (the TPU-native replacement for the reference's CUDA-event activity
    timings, gpu_operations.h:110-119)."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)


def stop_jax_profiler():
    import jax

    jax.profiler.stop_trace()
