"""Sharded, device-prefetching input pipeline utilities.

Reference analogue: the per-rank dataset sharding every Horovod example
does by hand (``dataset.shard(hvd.size(), hvd.rank())``,
examples/tensorflow2/tensorflow2_mnist.py) plus the Spark estimators'
per-rank readers (spark/common/util.py petastorm readers). TPU-native
re-design: batches are host numpy; ``prefetch_to_device`` keeps the next
batch's host→device transfer in flight while the current step computes —
the input-pipeline overlap a tf.data prefetch gives the reference.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

from ..common import context as ctx_mod


def shard_arrays(arrays: Sequence[np.ndarray], shard_id: Optional[int] = None,
                 num_shards: Optional[int] = None) -> list[np.ndarray]:
    """Per-worker strided shard of host arrays (reference
    ``dataset.shard(size, rank)`` convention — worker == process)."""
    if num_shards is None:
        num_shards = max(ctx_mod.cross_size(), 1)
    if shard_id is None:
        shard_id = ctx_mod.cross_rank() if num_shards > 1 else 0
    return [np.ascontiguousarray(a[shard_id::num_shards]) for a in arrays]


def batch_iterator(arrays: Sequence[np.ndarray], batch_size: int,
                   shuffle: bool = True, seed: int = 0,
                   drop_remainder: bool = True) -> Iterator[tuple]:
    """Epoch iterator over aligned arrays."""
    n = len(arrays[0])
    order = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(order)
    end = (n - n % batch_size) if drop_remainder else n
    for start in range(0, end, batch_size):
        idx = order[start:start + batch_size]
        yield tuple(a[idx] for a in arrays)


def prefetch_to_device(it: Iterable, size: int = 2,
                       device=None) -> Iterator:
    """Wrap a host-batch iterator so transfers overlap compute.

    Keeps up to ``size`` batches in flight via ``jax.device_put`` (async
    under the hood); yields device arrays in order. The double-buffering
    analogue of the reference's input-pipeline prefetch, on the
    host→HBM edge that is usually the TPU input bottleneck.
    """
    queue: collections.deque = collections.deque()

    def put(batch):
        return jax.tree.map(lambda x: jax.device_put(x, device), batch)

    it = iter(it)
    try:
        for _ in range(size):
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out


class ShardedLoader:
    """Convenience: shard → shuffle-per-epoch → batch → prefetch.

    .. code-block:: python

        loader = ShardedLoader((x, y), batch_size=128)
        for epoch in range(epochs):
            for bx, by in loader.epoch(epoch):
                state = step(state, bx, by)
    """

    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 prefetch: int = 2, drop_remainder: bool = True):
        self.arrays = shard_arrays(arrays)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = prefetch
        self.drop_remainder = drop_remainder

    def __len__(self) -> int:
        n = len(self.arrays[0])
        return n // self.batch_size if self.drop_remainder else \
            -(-n // self.batch_size)

    def epoch(self, epoch: int = 0) -> Iterator[tuple]:
        it = batch_iterator(self.arrays, self.batch_size, self.shuffle,
                            self.seed + epoch, self.drop_remainder)
        if self.prefetch > 0:
            return prefetch_to_device(it, self.prefetch)
        return it
