"""Canonical environment/flag schema.

The reference funnels all configuration through ~30 ``HOROVOD_*`` env vars
(/root/reference/horovod/common/common.h:66-96, parsed at
operations.cc:395-538 and utils/env_parser.cc). We keep the same three-layer
scheme (env vars < CLI flags that set env vars < YAML config file) with one
canonical table here so every subsystem reads configuration the same way.

Env vars keep the ``HOROVOD_`` prefix so existing user run-books transfer.
"""

from __future__ import annotations

import dataclasses
import os

# --- knob names (reference: common.h:66-96) ---------------------------------
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
# ragged-vs-dense eager alltoall crossover (nonzero cross edges)
HOROVOD_ALLTOALL_EDGE_LIMIT = "HOROVOD_ALLTOALL_EDGE_LIMIT"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
# joint fast-path autotuner (utils/autotune.py; docs/autotune.md):
# persisted winning-config file (all-or-nothing parse on reload), and the
# convergence guardrail — a candidate regressing the goodput score by
# >= REVERT_PCT percent for REVERT_WINDOWS consecutive sample windows is
# reverted to the best known config and penalized in the optimizer
HOROVOD_AUTOTUNE_TUNED_FILE = "HOROVOD_AUTOTUNE_TUNED_FILE"
HOROVOD_AUTOTUNE_REVERT_PCT = "HOROVOD_AUTOTUNE_REVERT_PCT"
HOROVOD_AUTOTUNE_REVERT_WINDOWS = "HOROVOD_AUTOTUNE_REVERT_WINDOWS"
# fused-plan granularity: max tensors per fused chunk (0 = byte-bounded
# only) — a joint-tuning knob (arXiv:2209.12769): smaller chunks overlap
# better, larger chunks amortize dispatches (ops/queue.py chunking)
HOROVOD_PLAN_CHUNK_TENSORS = "HOROVOD_PLAN_CHUNK_TENSORS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_RESPONSE_TIMEOUT_S = "HOROVOD_RESPONSE_TIMEOUT_S"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_BATCH_D2D_MEMCOPIES = "HOROVOD_BATCH_D2D_MEMCOPIES"
HOROVOD_NUM_NCCL_STREAMS = "HOROVOD_NUM_NCCL_STREAMS"  # accepted, ignored (no NCCL on TPU)
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
# metrics registry exposure (utils/metrics.py): periodic JSON dump path,
# dump/push interval in seconds, and the worker->launcher KV push toggle
HOROVOD_METRICS_FILE = "HOROVOD_METRICS_FILE"
HOROVOD_METRICS_DUMP_INTERVAL = "HOROVOD_METRICS_DUMP_INTERVAL"
HOROVOD_METRICS_PUSH = "HOROVOD_METRICS_PUSH"
# chaos fault-point spec + deterministic seed (utils/faults.py; see
# docs/fault_tolerance.md for the grammar)
HOROVOD_FAULT_SPEC = "HOROVOD_FAULT_SPEC"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"
# global overrides for every control-plane retry policy (utils/retry.py);
# call sites pass per-site defaults, these widen all of them at once
HOROVOD_RETRY_MAX_ATTEMPTS = "HOROVOD_RETRY_MAX_ATTEMPTS"
HOROVOD_RETRY_DEADLINE = "HOROVOD_RETRY_DEADLINE"
HOROVOD_RETRY_BASE_DELAY = "HOROVOD_RETRY_BASE_DELAY"
# elastic respawn-before-blacklist budget: per-host transient-failure
# retries and the backoff scale between respawn rounds (elastic/driver.py)
HOROVOD_ELASTIC_RESPAWN_ATTEMPTS = "HOROVOD_ELASTIC_RESPAWN_ATTEMPTS"
HOROVOD_ELASTIC_RESPAWN_BACKOFF = "HOROVOD_ELASTIC_RESPAWN_BACKOFF"
# elastic rendezvous identity: discovery epoch and reset generation
# (driver-injected, read by elastic/state.py and the controller's
# KV-scope prefix so stale rounds never cross a reset), plus the
# committed-state snapshot path for elastic restore (elastic/state.py)
HOROVOD_ELASTIC_EPOCH = "HOROVOD_ELASTIC_EPOCH"
HOROVOD_ELASTIC_GEN = "HOROVOD_ELASTIC_GEN"
HOROVOD_ELASTIC_STORE = "HOROVOD_ELASTIC_STORE"
# steady-state fast path (docs/performance.md): staging-ring slot count,
# escape hatch disabling compiled fused-chunk plans (legacy per-cycle
# eager dispatch), and the backend liveness-probe timeout in seconds
# (common/util.py probe_backend; the verdict is cached per process)
HOROVOD_STAGING_RING_SLOTS = "HOROVOD_STAGING_RING_SLOTS"
HOROVOD_FUSED_PLAN_DISABLE = "HOROVOD_FUSED_PLAN_DISABLE"
HOROVOD_BACKEND_PROBE_TIMEOUT = "HOROVOD_BACKEND_PROBE_TIMEOUT"
# cross-rank distributed tracing (utils/tracing.py; docs/timeline.md):
# master switch, buffered-span cap per rank, and a clock-offset override
# (seconds this rank's clock must be shifted to match the rendezvous
# coordinator's) replacing the NTP-style /clock estimation
HOROVOD_TRACE = "HOROVOD_TRACE"
HOROVOD_TRACE_BUFFER = "HOROVOD_TRACE_BUFFER"
HOROVOD_TRACE_CLOCK_OFFSET = "HOROVOD_TRACE_CLOCK_OFFSET"
# persistent jit compile cache directory toggle (utils/compile_cache.py)
HOROVOD_COMPILE_CACHE = "HOROVOD_COMPILE_CACHE"
# runtime lock-order/hold auditor (utils/lockcheck.py; docs/development.md):
# master switch and the held-too-long warning threshold in milliseconds
HOROVOD_LOCKCHECK = "HOROVOD_LOCKCHECK"
HOROVOD_LOCKCHECK_HOLD_MS = "HOROVOD_LOCKCHECK_HOLD_MS"
# ZeRO-1 sharded weight update (opt/sharded.py; docs/sharded_optimizer.md):
# master switch for the reduce-scatter → sharded step → allgather path in
# the framework shims, and the replicate threshold in elements below which
# a leaf stays on the classic allreduce path
HOROVOD_SHARDED_UPDATE = "HOROVOD_SHARDED_UPDATE"
HOROVOD_SHARDED_MIN_ELEMS = "HOROVOD_SHARDED_MIN_ELEMS"
# blockwise quantized wire format (ops/compression.py; docs/performance.md
# "Quantized allreduce"): none|int8|int4 selects the fused-chunk wire
# dtype, the per-block element count for absmax scales, the
# error-feedback master switch, the name-pattern opt-out list, and the
# small-leaf threshold in elements below which a tensor stays on the
# uncompressed path. Mutually exclusive with HOROVOD_SHARDED_UPDATE.
HOROVOD_COMPRESSION = "HOROVOD_COMPRESSION"
HOROVOD_QUANT_BLOCK = "HOROVOD_QUANT_BLOCK"
HOROVOD_QUANT_EF = "HOROVOD_QUANT_EF"
HOROVOD_QUANT_OPTOUT = "HOROVOD_QUANT_OPTOUT"
HOROVOD_QUANT_MIN_ELEMS = "HOROVOD_QUANT_MIN_ELEMS"
# native-core sanitizer build: address|thread adds the matching
# -fsanitize flags to the on-demand g++ build (_native/__init__.py)
HOROVOD_NATIVE_SANITIZE = "HOROVOD_NATIVE_SANITIZE"
# postmortem layer (utils/flightrec.py + utils/diag.py;
# docs/observability.md "Debugging a hung job"): flight-recorder master
# switch and ring capacity, the wedge-watchdog no-progress threshold in
# seconds (0 = off), and where diagnostic bundles are written
HOROVOD_FLIGHTREC = "HOROVOD_FLIGHTREC"
HOROVOD_FLIGHTREC_BUFFER = "HOROVOD_FLIGHTREC_BUFFER"
HOROVOD_WATCHDOG_SECS = "HOROVOD_WATCHDOG_SECS"
HOROVOD_DIAG_DIR = "HOROVOD_DIAG_DIR"
# per-step performance ledger + SLO budget engine (utils/perfledger.py;
# docs/observability.md "Performance ledger & SLO budgets"): master
# switch, per-step record-ring capacity, and the declarative budget spec
# — either the inline grammar ("negotiate_p95_ms<=5,plan_hit_rate>=0.95")
# or a JSON object / path to a JSON file mapping stat name to bound
HOROVOD_PERFLEDGER = "HOROVOD_PERFLEDGER"
HOROVOD_PERFLEDGER_BUFFER = "HOROVOD_PERFLEDGER_BUFFER"
HOROVOD_SLO_SPEC = "HOROVOD_SLO_SPEC"
# control-plane scale-out (ops/controller.py, ops/wire.py,
# runner/http_server.py; docs/scaling.md): hierarchical node-leader
# negotiation + binary wire-format v2 master switch, ranks per leader
# group (pods: set to the per-host process count), how long a member
# waits on its leader before falling back to flat submission, and the
# rendezvous KV shard count (listener sockets/stores in the launcher)
HOROVOD_HIER_NEGOTIATION = "HOROVOD_HIER_NEGOTIATION"
HOROVOD_HIER_GROUP_SIZE = "HOROVOD_HIER_GROUP_SIZE"
HOROVOD_HIER_FALLBACK_S = "HOROVOD_HIER_FALLBACK_S"
HOROVOD_KV_SHARDS = "HOROVOD_KV_SHARDS"
# device-memory & compile ledger (utils/memledger.py;
# docs/observability.md "Memory & compile ledger"): master switch and
# sample-ring capacity, plus an optional byte cap on the compiled-plan
# cache (ops/collectives.py) driving reason="memory" evictions from the
# per-plan program-size accounting (0 = uncapped)
HOROVOD_MEMLEDGER = "HOROVOD_MEMLEDGER"
HOROVOD_MEMLEDGER_BUFFER = "HOROVOD_MEMLEDGER_BUFFER"
HOROVOD_PLAN_CACHE_MAX_BYTES = "HOROVOD_PLAN_CACHE_MAX_BYTES"
# step-anatomy profiler (utils/anatomy.py; docs/observability.md "Step
# anatomy & headroom"): per-collective critical-path attribution and
# overlap/replay headroom estimation — master switch and per-step
# record-ring capacity
HOROVOD_ANATOMY = "HOROVOD_ANATOMY"
HOROVOD_ANATOMY_BUFFER = "HOROVOD_ANATOMY_BUFFER"
# whole-step megaplan capture & replay (ops/megaplan.py;
# docs/performance.md "Whole-step replay"): master switch, and how many
# consecutive identical working cycles (the response-cache/SAME_AS_LAST
# stability signal) must be observed before the full step schedule —
# negotiated order, chunk grouping, compiled chunk programs — is
# captured and steady-state cycles replay it with ~one validity check
HOROVOD_MEGAPLAN = "HOROVOD_MEGAPLAN"
HOROVOD_MEGAPLAN_STABLE_ROUNDS = "HOROVOD_MEGAPLAN_STABLE_ROUNDS"
# preemption-tolerant async sharded checkpointing (utils/async_ckpt.py;
# docs/fault_tolerance.md "Surviving preemption"): master switch, the
# directory shard checkpoints + manifest land in, and the SIGTERM grace
# window in seconds — the elastic driver waits this long between
# forwarding SIGTERM and escalating to SIGKILL, and the worker-side
# preemption handler bounds its final flush by the same budget
HOROVOD_ASYNC_CKPT = "HOROVOD_ASYNC_CKPT"
HOROVOD_ASYNC_CKPT_DIR = "HOROVOD_ASYNC_CKPT_DIR"
HOROVOD_PREEMPT_GRACE_S = "HOROVOD_PREEMPT_GRACE_S"
# fleet health engine (utils/health.py; docs/observability.md "Fleet
# health & history"): master switch, per-series history ring capacity,
# samples collected before the drift detector freezes its median/MAD
# baseline, and an optional path the full history rings are dumped to at
# shutdown (renderable by tools/benchtrend --from-history)
HOROVOD_HEALTH = "HOROVOD_HEALTH"
HOROVOD_HEALTH_BUFFER = "HOROVOD_HEALTH_BUFFER"
HOROVOD_HEALTH_WARMUP = "HOROVOD_HEALTH_WARMUP"
HOROVOD_HEALTH_FILE = "HOROVOD_HEALTH_FILE"

# ---------------------------------------------------------------------------
# Env-gated subsystems: master switch -> owning module. This mapping IS the
# machine-readable registry of the zero-cost contract — hvdlint's gate-prover
# pass (tools/hvdlint/passes/zerocost.py) parses it to decide which modules'
# hooks must pay at most one is-None check while disabled, and cross-checks
# it both ways: a module following the gated-trio pattern (enabled() reading
# a master switch + a module-global None handle) that is missing here fails
# lint, as does an entry whose module never reads its switch. Keys are the
# schema constants above; values are repo-relative module paths.
# ---------------------------------------------------------------------------
GATED_SUBSYSTEMS = {
    HOROVOD_TRACE: "horovod_tpu/utils/tracing.py",
    HOROVOD_FLIGHTREC: "horovod_tpu/utils/flightrec.py",
    HOROVOD_PERFLEDGER: "horovod_tpu/utils/perfledger.py",
    HOROVOD_MEMLEDGER: "horovod_tpu/utils/memledger.py",
    HOROVOD_ANATOMY: "horovod_tpu/utils/anatomy.py",
    HOROVOD_HEALTH: "horovod_tpu/utils/health.py",
    HOROVOD_MEGAPLAN: "horovod_tpu/ops/megaplan.py",
    HOROVOD_AUTOTUNE: "horovod_tpu/utils/autotune.py",
    HOROVOD_ASYNC_CKPT: "horovod_tpu/utils/async_ckpt.py",
    HOROVOD_LOCKCHECK: "horovod_tpu/utils/lockcheck.py",
}

# worker identity (reference: gloo_context.cc:136-192 reads the same set)
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"
HOROVOD_GLOO_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_GLOO_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_GLOO_IFACE = "HOROVOD_GLOO_IFACE"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
# per-job HMAC key authenticating every KV-store request/response
# (reference runner/common/util/secret.py); launcher-minted, env-injected
HOROVOD_SECRET_KEY = "HOROVOD_SECRET_KEY"

# TPU-specific (new in this framework)
HOROVOD_TPU_COORDINATOR = "HOROVOD_TPU_COORDINATOR"  # jax.distributed coordinator addr
HOROVOD_TPU_NUM_PROCESSES = "HOROVOD_TPU_NUM_PROCESSES"
HOROVOD_TPU_PROCESS_ID = "HOROVOD_TPU_PROCESS_ID"
HOROVOD_TPU_MESH = "HOROVOD_TPU_MESH"  # e.g. "dp=8" or "dp=4,tp=2"
# skip building/loading the native C++ core (numpy fallbacks everywhere)
HOROVOD_TPU_DISABLE_NATIVE = "HOROVOD_TPU_DISABLE_NATIVE"


def get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


def get_str(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


@dataclasses.dataclass
class RuntimeConfig:
    """Snapshot of all runtime knobs, read once at ``hvd.init()``.

    Mirrors the env-read block at reference operations.cc:395-538.

    - ``fusion_threshold_bytes``: fusion buffer size; reference default is
      128 MiB (operations.cc:446-451, env in MiB). On TPU this bounds how many
      pending eager tensors are flattened into one fused collective program.
    - ``cycle_time_ms``: background cycle sleep; reference default 1 ms
      (operations.cc:456).
    - ``cache_capacity``: response-cache entries (operations.cc:467); for us,
      max cached compiled fused-collective programs.
    """

    fusion_threshold_bytes: int = 128 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    timeline_filename: str = ""
    timeline_mark_cycles: bool = False
    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 20
    autotune_max_samples: int = 20
    # joint autotuner extras (docs/autotune.md): winning-config file and
    # the score-regression revert guardrail (X percent, K windows)
    autotune_tuned_file: str = ""
    autotune_revert_pct: float = 20.0
    autotune_revert_windows: int = 2
    # fused-plan granularity cap in tensors per chunk (0 = unbounded)
    plan_chunk_tensors: int = 0
    stall_check_disable: bool = False
    stall_warning_time_s: float = 60.0
    stall_shutdown_time_s: float = 0.0
    # how long a worker blocks on a negotiation-round response before
    # declaring the controller dead (coordinator failures error-close the
    # round proactively, so this is a backstop, not the common path)
    response_timeout_s: float = 300.0
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    elastic: bool = False
    metrics_file: str = ""
    metrics_dump_interval_s: float = 30.0
    metrics_push: bool = True
    # steady-state fast path: persistent staging slots per FusionBuffer and
    # the fused-plan escape hatch (legacy per-cycle eager dispatch)
    staging_ring_slots: int = 4
    fused_plan_disable: bool = False
    # cross-rank tracing (utils/tracing.py): spans, merged /timeline,
    # straggler attribution — off by default (zero-cost contract)
    trace_enabled: bool = False
    trace_buffer: int = 4096
    # ZeRO-1 sharded weight update (opt/sharded.py) — off by default;
    # the threshold mirrors sharding_policy.DEFAULT_MIN_SHARD_ELEMS
    sharded_update: bool = False
    sharded_min_elems: int = 2 ** 14
    # blockwise quantized wire (ops/compression.py) — "" keeps the wire
    # uncompressed (zero-cost contract: no hvd_quant_* series exist)
    compression: str = ""
    quant_block: int = 256
    quant_error_feedback: bool = True
    quant_optout: str = ""
    quant_min_elems: int = 4096
    # postmortem layer (utils/flightrec.py, utils/diag.py) — all off by
    # default (flight recorder zero-cost, watchdog thread not created)
    flightrec_enabled: bool = False
    flightrec_buffer: int = 2048
    watchdog_secs: float = 0.0
    diag_dir: str = ""
    # per-step performance ledger + SLO budgets (utils/perfledger.py) —
    # off by default (zero-cost contract: no hvd_perf_*/hvd_slo_* series)
    perfledger_enabled: bool = False
    perfledger_buffer: int = 1024
    slo_spec: str = ""
    # device-memory & compile ledger (utils/memledger.py) — off by
    # default (zero-cost contract: no hvd_mem_*/hvd_compile_* series);
    # plan_cache_max_bytes=0 leaves the plan cache entry-capped only
    memledger_enabled: bool = False
    memledger_buffer: int = 512
    plan_cache_max_bytes: int = 0
    # step-anatomy profiler (utils/anatomy.py) — off by default
    # (zero-cost contract: no hvd_anatomy_* series)
    anatomy_enabled: bool = False
    anatomy_buffer: int = 512
    # whole-step megaplan capture & replay (ops/megaplan.py) — off by
    # default (zero-cost contract: no hvd_megaplan_* series); the
    # stable-round count mirrors the reference response cache's
    # warmup-before-bypass behavior
    megaplan: bool = False
    megaplan_stable_rounds: int = 5
    # preemption-tolerant async sharded checkpointing (utils/async_ckpt.py)
    # — off by default (zero-cost contract: no hvd_ckpt_* series);
    # async_ckpt_dir="" resolves to ./horovod_ckpt at init
    async_ckpt: bool = False
    async_ckpt_dir: str = ""
    preempt_grace_s: float = 15.0
    # fleet health engine (utils/health.py) — off by default (zero-cost
    # contract: no hvd_health_* series); health_file="" skips the
    # on-exit history dump
    health_enabled: bool = False
    health_buffer: int = 512
    health_warmup: int = 20
    health_file: str = ""
    # control-plane scale-out (ops/controller.py + runner/http_server.py)
    # — off by default: the negotiation wire is byte-identical to the
    # flat/JSON v1 protocol and no hvd_hier_*/wire-v2 series exist
    hier_negotiation: bool = False
    hier_group_size: int = 8
    hier_fallback_s: float = 5.0
    kv_shards: int = 1

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        c = cls()
        mib = get_int(HOROVOD_FUSION_THRESHOLD, -1)
        if mib >= 0:
            # reference accepts raw bytes via HOROVOD_FUSION_THRESHOLD
            c.fusion_threshold_bytes = mib
        c.cycle_time_ms = get_float(HOROVOD_CYCLE_TIME, c.cycle_time_ms)
        c.cache_capacity = get_int(HOROVOD_CACHE_CAPACITY, c.cache_capacity)
        c.timeline_filename = get_str(HOROVOD_TIMELINE)
        c.timeline_mark_cycles = get_bool(HOROVOD_TIMELINE_MARK_CYCLES)
        c.autotune = get_bool(HOROVOD_AUTOTUNE)
        c.autotune_log = get_str(HOROVOD_AUTOTUNE_LOG)
        # same knob names as reference utils/env_parser.cc autotune block
        c.autotune_warmup_samples = get_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES,
                                            c.autotune_warmup_samples)
        c.autotune_steps_per_sample = get_int(HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE,
                                              c.autotune_steps_per_sample)
        c.autotune_max_samples = get_int(HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
                                         c.autotune_max_samples)
        c.autotune_tuned_file = get_str(HOROVOD_AUTOTUNE_TUNED_FILE)
        c.autotune_revert_pct = get_float(HOROVOD_AUTOTUNE_REVERT_PCT,
                                          c.autotune_revert_pct)
        c.autotune_revert_windows = get_int(HOROVOD_AUTOTUNE_REVERT_WINDOWS,
                                            c.autotune_revert_windows)
        c.plan_chunk_tensors = get_int(HOROVOD_PLAN_CHUNK_TENSORS,
                                       c.plan_chunk_tensors)
        c.stall_check_disable = get_bool(HOROVOD_STALL_CHECK_DISABLE)
        c.stall_warning_time_s = get_float(HOROVOD_STALL_CHECK_TIME_SECONDS, 60.0)
        c.stall_shutdown_time_s = get_float(HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0)
        c.response_timeout_s = get_float(HOROVOD_RESPONSE_TIMEOUT_S,
                                         c.response_timeout_s)
        c.hierarchical_allreduce = get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE)
        c.hierarchical_allgather = get_bool(HOROVOD_HIERARCHICAL_ALLGATHER)
        c.elastic = get_bool(HOROVOD_ELASTIC)
        c.metrics_file = get_str(HOROVOD_METRICS_FILE)
        c.metrics_dump_interval_s = get_float(HOROVOD_METRICS_DUMP_INTERVAL,
                                              c.metrics_dump_interval_s)
        c.metrics_push = get_bool(HOROVOD_METRICS_PUSH, True)
        c.staging_ring_slots = get_int(HOROVOD_STAGING_RING_SLOTS,
                                       c.staging_ring_slots)
        c.fused_plan_disable = get_bool(HOROVOD_FUSED_PLAN_DISABLE)
        c.trace_enabled = get_bool(HOROVOD_TRACE)
        c.trace_buffer = get_int(HOROVOD_TRACE_BUFFER, c.trace_buffer)
        c.sharded_update = get_bool(HOROVOD_SHARDED_UPDATE)
        c.sharded_min_elems = get_int(HOROVOD_SHARDED_MIN_ELEMS,
                                      c.sharded_min_elems)
        c.compression = get_str(HOROVOD_COMPRESSION).strip().lower()
        c.quant_block = get_int(HOROVOD_QUANT_BLOCK, c.quant_block)
        c.quant_error_feedback = get_bool(HOROVOD_QUANT_EF, True)
        c.quant_optout = get_str(HOROVOD_QUANT_OPTOUT)
        c.quant_min_elems = get_int(HOROVOD_QUANT_MIN_ELEMS,
                                    c.quant_min_elems)
        c.flightrec_enabled = get_bool(HOROVOD_FLIGHTREC)
        c.flightrec_buffer = get_int(HOROVOD_FLIGHTREC_BUFFER,
                                     c.flightrec_buffer)
        c.watchdog_secs = get_float(HOROVOD_WATCHDOG_SECS, c.watchdog_secs)
        c.diag_dir = get_str(HOROVOD_DIAG_DIR)
        c.perfledger_enabled = get_bool(HOROVOD_PERFLEDGER)
        c.perfledger_buffer = get_int(HOROVOD_PERFLEDGER_BUFFER,
                                      c.perfledger_buffer)
        c.slo_spec = get_str(HOROVOD_SLO_SPEC)
        c.memledger_enabled = get_bool(HOROVOD_MEMLEDGER)
        c.memledger_buffer = get_int(HOROVOD_MEMLEDGER_BUFFER,
                                     c.memledger_buffer)
        c.plan_cache_max_bytes = get_int(HOROVOD_PLAN_CACHE_MAX_BYTES,
                                         c.plan_cache_max_bytes)
        c.anatomy_enabled = get_bool(HOROVOD_ANATOMY)
        c.anatomy_buffer = get_int(HOROVOD_ANATOMY_BUFFER, c.anatomy_buffer)
        c.megaplan = get_bool(HOROVOD_MEGAPLAN)
        c.megaplan_stable_rounds = get_int(HOROVOD_MEGAPLAN_STABLE_ROUNDS,
                                           c.megaplan_stable_rounds)
        c.async_ckpt = get_bool(HOROVOD_ASYNC_CKPT)
        c.async_ckpt_dir = get_str(HOROVOD_ASYNC_CKPT_DIR)
        c.preempt_grace_s = get_float(HOROVOD_PREEMPT_GRACE_S,
                                      c.preempt_grace_s)
        c.health_enabled = get_bool(HOROVOD_HEALTH)
        c.health_buffer = get_int(HOROVOD_HEALTH_BUFFER, c.health_buffer)
        c.health_warmup = get_int(HOROVOD_HEALTH_WARMUP, c.health_warmup)
        c.health_file = get_str(HOROVOD_HEALTH_FILE)
        c.hier_negotiation = get_bool(HOROVOD_HIER_NEGOTIATION)
        c.hier_group_size = get_int(HOROVOD_HIER_GROUP_SIZE,
                                    c.hier_group_size)
        c.hier_fallback_s = get_float(HOROVOD_HIER_FALLBACK_S,
                                      c.hier_fallback_s)
        c.kv_shards = get_int(HOROVOD_KV_SHARDS, c.kv_shards)
        return c
