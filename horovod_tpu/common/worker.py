"""Worker-level (process) topology for the framework shims.

The framework shims' unit of data parallelism is the *process* — local
chips form one logical worker and the eager collectives reduce across
processes — so their ``rank()/size()/local_rank()/local_size()`` follow
the reference's process semantics: a verbatim
``DistributedSampler(num_replicas=hvd.size(), rank=hvd.rank())``
partitions correctly on multi-chip hosts, and the reference invariant
``local_size() <= size()`` holds (standalone, one process == one worker
== its own host). Chip-level topology stays on the core JAX API
(``horovod_tpu.rank()/size()/local_size()``).

Defined ONCE here and imported by the torch/tensorflow/keras/mxnet
shims (one semantic, four surfaces).
"""

from __future__ import annotations

import os

from . import context as _ctx
from . import env as _env


def rank() -> int:
    """Worker (process) rank — reference hvd.rank() semantics."""
    return _ctx.cross_rank()


def size() -> int:
    """Worker (process) count — reference hvd.size() semantics."""
    return _ctx.cross_size()


def local_rank() -> int:
    """This worker's rank among workers on the same host
    (launcher-injected; standalone a single process is its host's only
    worker, so 0 — NOT a chip index)."""
    v = os.environ.get(_env.HOROVOD_LOCAL_RANK)
    return int(v) if v is not None else 0


def local_size() -> int:
    """Workers on this host (launcher-injected; standalone 1, keeping
    the reference invariant local_size() <= size())."""
    v = os.environ.get(_env.HOROVOD_LOCAL_SIZE)
    return int(v) if v is not None else 1
