"""Small cross-framework helpers shared by the torch/TF/MXNet shims."""

from __future__ import annotations

import logging

LOG = logging.getLogger("horovod_tpu")

_warned_64bit = False


def warn_64bit_narrowing(dtype) -> None:
    """Reference Horovod preserves MPI_DOUBLE/MPI_LONG on the wire
    (common/wire/message.fbs DataType); this runtime narrows 64-bit values
    to 32-bit (JAX runs x64-disabled — TPUs have no f64 ALUs). Silent
    precision loss is unacceptable for e.g. f64 statistics, so say it once
    per process."""
    global _warned_64bit
    if not _warned_64bit:
        _warned_64bit = True
        LOG.warning(
            "collective input dtype %s rides the wire as 32-bit (JAX x64 is "
            "disabled; TPUs have no float64 units). The caller dtype is "
            "restored on output but precision beyond 32 bits is lost. See "
            "docs/frameworks.md.", dtype)


def module_namespace(mod, **extra):
    """A SimpleNamespace copy of ``mod``'s public attributes with
    framework-specific additions grafted on — used by the shims to
    present ``hvd.elastic`` (etc.) with extra classes without mutating
    the shared module."""
    import types

    ns = types.SimpleNamespace(
        **{k: getattr(mod, k) for k in dir(mod) if not k.startswith("_")})
    for k, v in extra.items():
        setattr(ns, k, v)
    return ns
