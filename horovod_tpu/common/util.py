"""Small cross-framework helpers shared by the torch/TF/MXNet shims."""

from __future__ import annotations

import contextlib
import logging
import os
import tempfile

LOG = logging.getLogger("horovod_tpu")

# process umask, read once at import (single-threaded) — os.umask() is
# process-global and racy to query from concurrent writers
_UMASK = os.umask(0)
os.umask(_UMASK)


@contextlib.contextmanager
def atomic_tmp(path: str, mode: int | None = 0o666):
    """Yield a unique tmp filename next to ``path``; atomically commit it
    over ``path`` on clean exit, remove it on error.

    The single atomic-replace implementation for every concurrent writer
    in the runtime (store chunks, pickle checkpoints, the native-lib
    build): N launcher workers write the same artifact simultaneously, so
    tmp names must be per-call unique (a shared name lets one worker
    truncate the file another is mid-writing and makes the loser's
    ``os.replace`` fail with FileNotFoundError) and the tmp must live in
    the target's directory so the rename stays on one filesystem.
    ``mode`` restores plain-``open()`` permissions at commit (mkstemp
    creates 0600; shared stores are read across uids) — best-effort, and
    ``None`` keeps the tmp's mode.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path),
                               suffix=".tmp")
    os.close(fd)
    try:
        yield tmp
        if mode is not None:
            try:
                os.chmod(tmp, mode & ~_UMASK)
            except OSError:  # e.g. some CIFS/FUSE mounts — keep the write
                pass
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_bytes(path: str, data: bytes, mode: int | None = 0o666):
    """Concurrency-safe whole-file write via :func:`atomic_tmp`."""
    with atomic_tmp(path, mode=mode) as tmp:
        with open(tmp, "wb") as f:
            f.write(data)

_warned_64bit = False


def warn_64bit_narrowing(dtype) -> None:
    """Reference Horovod preserves MPI_DOUBLE/MPI_LONG on the wire
    (common/wire/message.fbs DataType); this runtime narrows 64-bit values
    to 32-bit (JAX runs x64-disabled — TPUs have no f64 ALUs). Silent
    precision loss is unacceptable for e.g. f64 statistics, so say it once
    per process."""
    global _warned_64bit
    if not _warned_64bit:
        _warned_64bit = True
        LOG.warning(
            "collective input dtype %s rides the wire as 32-bit (JAX x64 is "
            "disabled; TPUs have no float64 units). The caller dtype is "
            "restored on output but precision beyond 32 bits is lost. See "
            "docs/frameworks.md.", dtype)


# probe_backend verdict, cached for the process lifetime: a wedged TPU
# tunnel makes EVERY probe hang for the full timeout, and one stall per
# process is the most a verdict is worth (BENCH_r05 burned 120 s on it;
# repeated probes would burn it again per call site)
_BACKEND_PROBE_VERDICT: dict = {}

PROBE_SENTINEL = "BENCH-PROBE-OK"


def probe_backend_timeout() -> float:
    """Backend-probe timeout in seconds (HOROVOD_BACKEND_PROBE_TIMEOUT,
    default 120 — the historical hardcoded value)."""
    from . import env as env_mod

    t = env_mod.get_float(env_mod.HOROVOD_BACKEND_PROBE_TIMEOUT, 120.0)
    return t if t > 0 else 120.0


def probe_backend(timeout_s: float | None = None, force: bool = False):
    """Decide whether the JAX backend is usable, in a THROWAWAY subprocess.

    A wedged TPU tunnel hangs inside backend init instead of raising, so
    an in-process probe would hang the caller. Returns ``(ok, err)`` where
    ``err`` is a short diagnostic when ``ok`` is False. The verdict is
    cached for the process lifetime (``force=True`` re-probes)."""
    import subprocess
    import sys

    if not force and "verdict" in _BACKEND_PROBE_VERDICT:
        return _BACKEND_PROBE_VERDICT["verdict"]
    if timeout_s is None:
        timeout_s = probe_backend_timeout()
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             f"import jax; jax.devices(); print('{PROBE_SENTINEL}')"],
            env=dict(os.environ), timeout=timeout_s,
            capture_output=True, text=True)
        ok = PROBE_SENTINEL in p.stdout
        err = "" if ok else (p.stderr or "backend probe failed")[-400:]
    except Exception as e:  # TimeoutExpired, OSError
        ok = False
        err = (f"backend probe hung for {timeout_s:g} s (wedged tunnel)"
               if isinstance(e, subprocess.TimeoutExpired)
               else f"backend probe failed to launch: {e}")
    from ..utils import flightrec

    flightrec.note("probe_verdict", ok=ok, err=err)
    _BACKEND_PROBE_VERDICT["verdict"] = (ok, err)
    return ok, err


def clear_backend_probe_cache():
    """Forget the cached probe verdict (test helper)."""
    _BACKEND_PROBE_VERDICT.clear()


def module_namespace(mod, **extra):
    """A SimpleNamespace copy of ``mod``'s public attributes with
    framework-specific additions grafted on — used by the shims to
    present ``hvd.elastic`` (etc.) with extra classes without mutating
    the shared module."""
    import types

    ns = types.SimpleNamespace(
        **{k: getattr(mod, k) for k in dir(mod) if not k.startswith("_")})
    for k, v in extra.items():
        setattr(ns, k, v)
    return ns
