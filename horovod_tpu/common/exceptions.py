"""Exception types for horovod_tpu.

TPU-native equivalents of the reference's exception surface
(/root/reference/horovod/common/exceptions.py:17-34): ``HorovodInternalError``
is raised when a collective fails mid-flight (elastic mode catches it and
restores committed state), ``HostsUpdatedInterrupt`` is raised when cluster
membership changes under elastic training.
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective routine fails.

    Elastic training (`horovod_tpu.elastic.run`) catches this, restores the
    last committed state, re-initializes the process set, and retries.
    """


class HostsUpdatedInterrupt(RuntimeError):
    """Raised when cluster membership changed during an elastic run.

    ``skip_sync`` mirrors the reference semantics: when the update was
    graceful (no failure), state does not need to be restored from the last
    commit (/root/reference/horovod/common/exceptions.py:27-33).
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


class TensorShapeMismatchError(ValueError):
    """Cross-rank shape mismatch detected during negotiation.

    The reference controller constructs an ERROR response when ranks submit
    the same tensor name with inconsistent shapes
    (/root/reference/horovod/common/controller.cc:471-748). We raise eagerly
    at enqueue/validation time instead.
    """


class TensorDtypeMismatchError(ValueError):
    """Cross-rank dtype mismatch (controller.cc:538-556 equivalent)."""


class DuplicateNameError(ValueError):
    """A tensor with the same name is already in flight.

    Mirrors DUPLICATE_NAME_ERROR (/root/reference/horovod/common/common.h:169).
    """


class StalledTensorError(RuntimeError):
    """Raised when stalled tensors force a shutdown.

    Mirrors the stall-inspector shutdown path
    (/root/reference/horovod/common/stall_inspector.cc; env
    ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``).
    """


class FaultInjectedError(RuntimeError):
    """A chaos fault fired at a ``HOROVOD_FAULT_SPEC`` fault point
    (``utils/faults.py``). Only ever raised when fault injection is
    explicitly configured; production code paths never see it.

    ``drop``-mode faults raise the ``FaultInjectedConnectionError``
    subclass (also a ``ConnectionError``) so transport retry policies
    classify them exactly like a real dropped socket.
    """


class RetriesExhaustedError(RuntimeError):
    """A :class:`horovod_tpu.utils.retry.Retrier` ran out of budget
    (attempts or deadline) with no attempt ever classified retryable —
    e.g. the overall deadline expired before the first try. When attempts
    *were* made, the Retrier re-raises the last real exception instead,
    so callers keep their existing except clauses.
    """

    def __init__(self, site: str, attempts: int, elapsed_s: float):
        super().__init__(
            f"retry budget exhausted at {site!r}: {attempts} attempt(s) "
            f"over {elapsed_s:.1f}s")
        self.site = site
        self.attempts = attempts
        self.elapsed_s = elapsed_s
