"""Global runtime context: init/shutdown, process sets, device mesh.

TPU-native re-design of the reference's process-global state + background
runtime (`HorovodGlobalState`, /root/reference/horovod/common/global_state.h:43;
`InitializeHorovodOnce`, operations.cc:649). Key differences, by design:

- On GPU-Horovod, one process == one GPU == one rank, and every collective is
  negotiated between processes over MPI/Gloo and executed by NCCL.
- On TPU, one Python process drives ``local_size()`` chips and collectives are
  XLA programs over a `jax.sharding.Mesh` riding ICI (intra-slice) / DCN
  (cross-slice). SPMD programs are already symmetric across chips, so the
  per-tensor negotiation protocol (controller.cc:69 ComputeResponseList)
  collapses for the compiled path; it survives (slim, in
  `horovod_tpu.ops.queue`) only for the eager/dynamic path.

Rank/size vocabulary (documented contract):

- ``size()``   — total number of chips in the set (the data-parallel width a
                 Horovod user expects for LR scaling).
- ``rank()``   — global index of this process's first chip. ``rank() == 0``
                 is true exactly on the coordinator process, so rank-0
                 checkpoint/log idioms transfer unchanged.
- ``local_size()`` / ``local_rank()`` — under a launcher, worker processes
                 on this host / this worker's index among them (the
                 launcher-injected HOROVOD_LOCAL_* env wins); standalone,
                 chips driven by this process / 0.
- ``cross_size()`` / ``cross_rank()`` — number of processes / this process's
                 index (the reference's cross-communicator,
                 mpi_context.cc:147-156).

Per-chip rank only exists *inside* compiled programs, via
``jax.lax.axis_index(axis_name)`` — that is the TPU-native shape of the
reference's per-GPU rank.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from . import env as env_schema
from .env import RuntimeConfig
from .exceptions import HorovodInternalError

LOG = logging.getLogger("horovod_tpu")

# Default axis name used by every collective when tracing inside shard_map.
DEFAULT_AXIS = "hvd"
# Process-level and local axes of the 2-D eager mesh.
PROC_AXIS = "hvd_proc"
LOCAL_AXIS = "hvd_local"


def _sorted_devices():
    """All addressable+global devices in (process_index, id) order."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


class ProcessSet:
    """A named subset of chips with its own meshes.

    TPU-native equivalent of an MPI (sub-)communicator
    (/root/reference/horovod/common/basics.py:33-65 accepts ``comm``/ranks;
    mpi_context.cc builds GLOBAL/LOCAL/CROSS comms). A ProcessSet owns:

    - ``mesh``      — 1-D mesh over all member chips, axis ``"hvd"``; the
                      data plane for flat collectives.
    - ``mesh_2d``   — (process, local-chip) mesh, axes ``("hvd_proc",
                      "hvd_local")``; used by eager process-level collectives
                      and by hierarchical (intra-host ICI / cross-host DCN)
                      strategies — the reference's LOCAL/CROSS communicator
                      triad (common.h:119-123).
    """

    def __init__(self, name: str, devices: Sequence[jax.Device]):
        self.name = name
        self.devices = list(devices)
        n = len(self.devices)
        if n == 0:
            raise ValueError("ProcessSet needs at least one device")
        dev_arr = np.array(self.devices, dtype=object)
        self.mesh = Mesh(dev_arr, (DEFAULT_AXIS,))
        # group by owning process
        procs = sorted({d.process_index for d in self.devices})
        self._proc_indices = procs
        by_proc = [[d for d in self.devices if d.process_index == p] for p in procs]
        local_counts = {len(g) for g in by_proc}
        if len(local_counts) == 1:
            self.is_homogeneous = True
            self.mesh_2d = Mesh(
                np.array(by_proc, dtype=object), (PROC_AXIS, LOCAL_AXIS)
            )
        else:
            # heterogeneous local counts: no rectangular 2-D mesh; eager path
            # falls back to the flat mesh
            self.is_homogeneous = False
            self.mesh_2d = None

    # --- sizes -------------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def local_devices(self):
        pid = jax.process_index()
        return [d for d in self.devices if d.process_index == pid]

    @property
    def local_size(self) -> int:
        return len(self.local_devices)

    @property
    def rank(self) -> int:
        """Global chip index of this process's first member device."""
        pid = jax.process_index()
        for i, d in enumerate(self.devices):
            if d.process_index == pid:
                return i
        raise HorovodInternalError(
            f"process {pid} owns no devices in process set {self.name!r}"
        )

    @property
    def cross_size(self) -> int:
        return len(self._proc_indices)

    @property
    def cross_rank(self) -> int:
        return self._proc_indices.index(jax.process_index())

    def included(self) -> bool:
        pid = jax.process_index()
        return any(d.process_index == pid for d in self.devices)

    def __repr__(self):
        return f"ProcessSet({self.name!r}, size={self.size})"


class _Context:
    """Process-global singleton (HorovodGlobalState equivalent)."""

    def __init__(self):
        self.lock = threading.RLock()
        self.initialized = False
        self.config: RuntimeConfig = RuntimeConfig()
        self.global_set: Optional[ProcessSet] = None
        self.process_sets: dict[str, ProcessSet] = {}
        self.runtime = None  # ops.queue.BackgroundRuntime, set by init()
        self.timeline = None  # utils.timeline.Timeline
        self.stall_inspector = None
        self.autotuner = None
        self.metrics_dumper = None  # utils.metrics.MetricsDumper
        self.joined = False  # reference global_state.h:107-111


_ctx = _Context()


def context() -> _Context:
    return _ctx


def _maybe_init_distributed():
    """Multi-host bootstrap: jax.distributed replaces MPI rendezvous.

    The launcher (horovod_tpu.runner) sets HOROVOD_TPU_COORDINATOR /
    NUM_PROCESSES / PROCESS_ID, the TPU-native equivalent of the env the
    reference's gloo launcher injects (gloo_run.py:65 create_slot_env_vars).
    """
    coord = os.environ.get(env_schema.HOROVOD_TPU_COORDINATOR)
    if not coord:
        return
    nproc = int(os.environ.get(env_schema.HOROVOD_TPU_NUM_PROCESSES, "1"))
    if nproc <= 1:
        return
    # IMPORTANT: do not touch jax.devices()/process_count() before this —
    # any backend-initializing call makes jax.distributed.initialize
    # impossible (it must run first in the process).
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "client", None) is not None:
        return  # already initialized
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=int(os.environ[env_schema.HOROVOD_TPU_PROCESS_ID]),
        )
        LOG.info("jax.distributed initialized via %s", coord)
        _install_fatal_exit_hook()
    except Exception as e:
        LOG.warning("jax.distributed.initialize failed: %s", e)


def _install_fatal_exit_hook():
    """A distributed worker that dies of an unhandled exception must
    EXIT, not linger: interpreter teardown destroys the jax.distributed
    client, whose destructor blocks on the coordination-service shutdown
    barrier until the surviving peers also exit (measured: a failing rank
    stayed alive ~5 min while its healthy peer sat in a negotiation
    poll). The launcher's first-failure kill (reference gloo_run.py:
    263-271) can only fire once this process is actually gone — so after
    reporting the error we flush and hard-exit before teardown reaches
    that destructor. Normal completion and sys.exit() keep the clean
    path (the barrier is then bounded by real rank skew).

    Scope: only launcher-spawned workers (HOROVOD_RANK in the env) get
    the hook — a user-embedded driver that initializes jax.distributed
    itself keeps standard teardown (atexit handlers, coverage, tempfile
    cleanup). KeyboardInterrupt keeps its conventional 130 exit code.
    (Uncaught SystemExit never reaches sys.excepthook — the interpreter
    handles it first — so sys.exit() takes the normal teardown path,
    which is the desired behavior anyway.)"""
    import sys

    if os.environ.get(env_schema.HOROVOD_RANK) is None:
        return

    prev = sys.excepthook

    def hook(tp, val, tb):
        code = 1
        if issubclass(tp, KeyboardInterrupt):
            code = 130  # 128 + SIGINT, the shell convention
        try:
            # inside the try: a raising prev hook (or a torn-down stderr
            # pipe) must not skip the hard exit — lingering is the exact
            # failure this hook exists to prevent
            prev(tp, val, tb)
            sys.stdout.flush()
            sys.stderr.flush()
        finally:
            os._exit(code)

    sys.excepthook = hook


def init(ranks: Optional[Sequence[int]] = None, *, start_runtime: bool = True):
    """Initialize horovod_tpu (reference: hvd.init(), basics.py:33).

    ``ranks`` optionally restricts the global process set to a subset of chip
    indices — the moral equivalent of ``hvd.init(comm=ranks)``.

    Unlike the reference there is no background *communication* thread to
    spawn for the compiled path — XLA executes collectives inline in program
    order over ICI. ``start_runtime`` starts the slim background cycle loop
    that serves the *eager/async named-tensor* API
    (`horovod_tpu.ops.queue.BackgroundRuntime`, the TPU-shaped remnant of
    BackgroundThreadLoop, operations.cc:353).
    """
    with _ctx.lock:
        if _ctx.initialized:
            return
        _maybe_init_distributed()
        _ctx.config = RuntimeConfig.from_env()
        devices = _sorted_devices()
        if ranks is not None:
            devices = [devices[i] for i in ranks]
        _ctx.global_set = ProcessSet("global", devices)
        _ctx.process_sets = {"global": _ctx.global_set}
        _ctx.joined = False

        # postmortem layer BEFORE the runtime/controller construct: both
        # resolve the recorder/watchdog handles once at build time
        _start_diag()

        # perf ledger BEFORE the runtime construct for the same reason;
        # the SLO engine attaches the stall inspector below once it exists
        from ..utils import perfledger as perfledger_mod

        perfledger_mod.init_ledger(rank=_ctx.global_set.cross_rank)

        # device-memory & compile ledger, same placement rationale: the
        # plan-build instrumentation in ops/collectives.py checks the
        # ledger handle at plan-cache-miss time
        from ..utils import memledger as memledger_mod

        memledger_mod.init_ledger(rank=_ctx.global_set.cross_rank)

        # step-anatomy profiler, same placement rationale: the queue's
        # dispatch hooks resolve the profiler handle once at build time
        from ..utils import anatomy as anatomy_mod

        anatomy_mod.init_profiler(rank=_ctx.global_set.cross_rank)

        # megaplan capture/replay manager, same placement rationale: the
        # runtime resolves the manager handle once at build time (and the
        # coordinator reads the same env gate in its own __init__)
        from ..ops import megaplan as megaplan_mod

        megaplan_mod.init_manager(rank=_ctx.global_set.cross_rank)

        # async shard checkpointer AFTER _start_diag(): its SIGTERM
        # handler must capture diag's as the chain target, so a
        # preemption flushes the in-flight snapshot first and dumps the
        # diagnostic bundle second
        from ..utils import async_ckpt as async_ckpt_mod

        async_ckpt_mod.init_checkpointer(
            rank=_ctx.global_set.cross_rank,
            world=_ctx.global_set.cross_size)

        # fleet health engine, same placement rationale as the ledgers:
        # the MetricsDumper flush hook checks the engine handle per pass
        from ..utils import health as health_mod

        health_mod.init_engine(rank=_ctx.global_set.cross_rank)

        if _ctx.config.trace_enabled:
            # before the runtime/controller construct: both resolve the
            # tracer once at build time (zero-cost None when off)
            from ..utils import tracing as tracing_mod

            tracing_mod.init_tracer(
                rank=_ctx.global_set.cross_rank,
                addr=os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR),
                port=os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT))

        from ..utils.timeline import Timeline

        # the reference's timeline is recorded by the coordinator only
        # (operations.cc BackgroundThreadLoop gates on rank 0); same here —
        # also prevents same-host ranks clobbering one file
        tl_file = (_ctx.config.timeline_filename
                   if _ctx.global_set.cross_rank == 0 else "")
        _ctx.timeline = Timeline(tl_file,
                                 mark_cycles=_ctx.config.timeline_mark_cycles)

        if start_runtime:
            from ..ops.queue import BackgroundRuntime
            from ..utils.stall import StallInspector

            _ctx.stall_inspector = StallInspector(
                warning_time_s=_ctx.config.stall_warning_time_s,
                shutdown_time_s=_ctx.config.stall_shutdown_time_s,
                disabled=_ctx.config.stall_check_disable,
            )
            # idempotent: hands the inspector to an already-armed SLO
            # engine so breach escalations carry straggler attribution
            perfledger_mod.init_ledger(
                rank=_ctx.global_set.cross_rank,
                stall_inspector=_ctx.stall_inspector)
            # same handover for the health engine: anomaly escalations
            # carry straggler attribution once the inspector exists
            health_mod.init_engine(
                rank=_ctx.global_set.cross_rank,
                stall_inspector=_ctx.stall_inspector)
            _ctx.runtime = BackgroundRuntime(
                _ctx.global_set,
                config=_ctx.config,
                timeline=_ctx.timeline,
                stall_inspector=_ctx.stall_inspector,
            )
            _ctx.runtime.start()
            from ..utils import flightrec as flightrec_mod

            flightrec_mod.note("init_phase", phase="runtime_started")
            if _ctx.config.autotune:
                from ..utils.autotune import Autotuner

                _ctx.autotuner = Autotuner(
                    _ctx.runtime, log_path=_ctx.config.autotune_log,
                    warmup_samples=_ctx.config.autotune_warmup_samples,
                    max_samples=_ctx.config.autotune_max_samples,
                    config=_ctx.config)
                _ctx.runtime.autotuner = _ctx.autotuner
                _ctx.runtime.autotune_steps_per_sample = (
                    _ctx.config.autotune_steps_per_sample)
                # hand the tuner to the health engine so a latched
                # goodput drift feeds the workload-shift re-tune path
                health_mod.init_engine(
                    rank=_ctx.global_set.cross_rank,
                    autotuner=_ctx.autotuner)
        _start_metrics_dumper()
        _ctx.initialized = True
        from ..utils import flightrec as flightrec_mod

        flightrec_mod.note("init_phase", phase="initialized")
        LOG.info("horovod_tpu initialized: %s", _ctx.global_set)


def _start_diag():
    """Arm the postmortem layer (utils/flightrec.py + utils/diag.py):
    the flight recorder (``HOROVOD_FLIGHTREC``), the wedge watchdog
    (``HOROVOD_WATCHDOG_SECS`` > 0), the signal/crash dump hooks, and —
    in a launched job — a dedicated KV client so watchdog/crash bundles
    ride the push path into the launcher's ``GET /debug``. The memory
    ledger (``HOROVOD_MEMLEDGER``) arms the same path for its OOM
    forensics. With all knobs off, nothing is created and no hook is
    installed."""
    from ..utils import diag as diag_mod
    from ..utils import flightrec as flightrec_mod

    from ..utils import memledger as memledger_mod

    recorder = flightrec_mod.init_recorder(rank=_ctx.global_set.cross_rank)
    flightrec_mod.note("init_phase", phase="config")
    wd = diag_mod.init_watchdog(_ctx.config.watchdog_secs)
    # the memory ledger is a third reason to arm the dump path: its OOM
    # forensics contract is "an allocation failure yields a pushed oom
    # bundle the launcher's GET /debug can attribute", with no flight
    # recorder or watchdog required
    if recorder is None and wd is None and not memledger_mod.enabled():
        return
    addr = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR)
    port = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT)
    if addr and port:
        from ..runner.http_server import KVStoreClient

        # NOT the MetricsDumper's client: dumps fire from the watchdog /
        # signal context concurrently with the dumper cadence, and the
        # keep-alive socket is per-thread state
        diag_mod.set_kv_client(KVStoreClient(addr, int(port)))
    # after _install_fatal_exit_hook (in _maybe_init_distributed), so the
    # excepthook chain runs dump-first, then print-and-os._exit
    diag_mod.install_crash_hooks()


def _start_metrics_dumper():
    """Start the metrics publisher when there is somewhere to publish:
    a ``HOROVOD_METRICS_FILE`` path and/or (in a launched job) the
    launcher's KV store, where pushed snapshots feed its ``GET /metrics``.
    With neither, no thread is created at all — standalone single-process
    use pays nothing for the subsystem."""
    from ..utils import metrics as metrics_mod

    crank = _ctx.global_set.cross_rank
    path = _ctx.config.metrics_file
    if path and crank != 0:
        # every rank's dump is a distinct post-mortem artifact; same-host
        # ranks share the env value, so suffix to avoid clobbering
        path = f"{path}.rank{crank}"
    kv = None
    addr = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR)
    port = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT)
    if _ctx.config.metrics_push and addr and port:
        from ..runner.http_server import KVStoreClient

        kv = KVStoreClient(addr, int(port))
    if not path and kv is None:
        return
    _ctx.metrics_dumper = metrics_mod.MetricsDumper(
        metrics_mod.get_registry(), file_path=path,
        interval_s=_ctx.config.metrics_dump_interval_s,
        kv_client=kv, rank=crank)
    _ctx.metrics_dumper.start()


def shutdown(drain: bool = True):
    """Tear down (reference: horovod_shutdown, operations.cc:728).

    Pending async operations fail with HorovodInternalError, mirroring
    FinalizeTensorQueue (tensor_queue.h:35). ``drain=False`` skips the
    cooperative shutdown barrier — for error-recovery teardown
    (elastic reinit), where waiting on a broken lockstep only delays
    the new generation.
    """
    with _ctx.lock:
        if not _ctx.initialized:
            return
        if _ctx.runtime is not None:
            _ctx.runtime.stop(drain=drain)
            _ctx.runtime = None
        if _ctx.timeline is not None:
            _ctx.timeline.close()
            _ctx.timeline = None
        if _ctx.metrics_dumper is not None:
            # stop() performs a final flush: the metrics file / KV push
            # reflects everything the drained runtime counted
            _ctx.metrics_dumper.stop()
            _ctx.metrics_dumper = None
        from ..utils import health as health_mod

        # after the dumper's final flush so the HOROVOD_HEALTH_FILE dump
        # carries the last sampled window (engine survives shutdown like
        # the ledgers: one continuous history per process)
        health_mod.dump_on_exit()
        from ..utils import diag as diag_mod

        # the flight recorder survives shutdown (one continuous ring per
        # process, like the metrics registry); the watchdog thread and
        # its KV client do not
        diag_mod.reset_watchdog()
        diag_mod.set_kv_client(None)
        _ctx.stall_inspector = None
        _ctx.autotuner = None
        _ctx.global_set = None
        _ctx.process_sets = {}
        _ctx.initialized = False


atexit.register(shutdown)


def _require_init() -> _Context:
    if not _ctx.initialized:
        raise ValueError(
            "horovod_tpu has not been initialized; call horovod_tpu.init() first."
        )
    return _ctx


def is_initialized() -> bool:
    return _ctx.initialized


def global_process_set() -> ProcessSet:
    return _require_init().global_set


def add_process_set(ranks: Sequence[int], name: Optional[str] = None) -> ProcessSet:
    """Create a sub-communicator over a subset of global chip indices."""
    ctx = _require_init()
    name = name or f"set_{','.join(map(str, ranks))}"
    with ctx.lock:
        if name in ctx.process_sets:
            return ctx.process_sets[name]
        devs = [ctx.global_set.devices[i] for i in ranks]
        ps = ProcessSet(name, devs)
        ctx.process_sets[name] = ps
        return ps


def remove_process_set(name: str):
    ctx = _require_init()
    with ctx.lock:
        if name == "global":
            raise ValueError("cannot remove the global process set")
        ctx.process_sets.pop(name, None)


# --- rank/size API (reference: operations.cc:766-910, basics.py) ------------

def size() -> int:
    return _require_init().global_set.size


def rank() -> int:
    return _require_init().global_set.rank


def local_size() -> int:
    """Under a launcher (multi-process-per-host), the number of worker
    processes on this host (launcher-injected env, reference
    gloo_context.cc:136-192 consumption); standalone, the chips this
    process drives — the TPU-sensible analogue."""
    ctx = _require_init()
    v = os.environ.get(env_schema.HOROVOD_LOCAL_SIZE)
    if v is not None:
        return int(v)
    return ctx.global_set.local_size


def local_rank() -> int:
    """This process's rank among processes on the same host.

    Standalone (no launcher env) this is 0: ONE process drives ALL local
    chips here, unlike the reference's process-per-GPU model. A ported
    script that maps ``local_rank()`` to a device index
    (``torch.cuda.set_device(hvd.local_rank())``-style) would silently
    address only device 0 — iterate ``jax.local_devices()`` or shard over
    the process set's mesh instead (see docs/running.md)."""
    ctx = _require_init()
    v = os.environ.get(env_schema.HOROVOD_LOCAL_RANK)
    if v is not None:
        return int(v)
    return 0 if ctx.global_set.local_size > 0 else -1


def cross_size() -> int:
    return _require_init().global_set.cross_size


def cross_rank() -> int:
    return _require_init().global_set.cross_rank


def is_homogeneous() -> bool:
    """True when every process drives the same number of chips
    (reference: horovod_is_homogeneous, operations.cc:840)."""
    return _require_init().global_set.is_homogeneous


def shard_id() -> int:
    """Input-pipeline shard index for this process (== cross_rank()).

    New helper: on TPU, datasets shard per *process*, not per chip.
    """
    return cross_rank()


def num_shards() -> int:
    return cross_size()


# --- capability probes (reference: operations.cc:846-910) --------------------

def mpi_threads_supported() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def tpu_built() -> bool:
    """The one that matters here."""
    return True


def tpu_enabled() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def start_timeline(filename: str, mark_cycles: bool = False):
    """Runtime timeline control (reference operations.cc:738-764)."""
    ctx = _require_init()
    ctx.timeline.reopen(filename, mark_cycles=mark_cycles)


def stop_timeline():
    ctx = _require_init()
    ctx.timeline.reopen("", mark_cycles=False)
