"""Training-loop callbacks — the Keras-callback surface, JAX-shaped.

Reference: /root/reference/horovod/_keras/callbacks.py +
keras/callbacks.py — `BroadcastGlobalVariablesCallback`,
`MetricAverageCallback`, `LearningRateWarmupCallback`,
`LearningRateScheduleCallback`, elastic `CommitStateCallback` /
`UpdateBatchStateCallback`.

JAX training loops are explicit, so these are small callables invoked from
the loop (flax has no global callback registry); each documents the
reference callback it replaces. The LR schedules are optax-composable.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
import optax

from . import broadcast_parameters
from .ops import collectives as C


class BroadcastGlobalVariablesCallback:
    """Broadcast params (+opt state) from root once, at train start
    (reference keras/callbacks.py BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def __call__(self, params, opt_state=None):
        if self._done:
            return (params, opt_state) if opt_state is not None else params
        params = broadcast_parameters(params, self.root_rank)
        if opt_state is not None:
            opt_state = jax.tree.map(
                lambda x: C.broadcast(x, self.root_rank)
                if hasattr(x, "dtype") else x, opt_state)
        # only latch after the broadcast succeeded — a failed first call
        # must not silently disable synchronization on retry
        self._done = True
        return (params, opt_state) if opt_state is not None else params


class MetricAverageCallback:
    """Average epoch metrics across workers before logging (reference
    MetricAverageCallback: allreduce of logs at epoch end)."""

    def __call__(self, metrics: dict) -> dict:
        out = {}
        for k, v in metrics.items():
            out[k] = float(np.asarray(
                C.allreduce(np.asarray(v, np.float32), average=True)))
        return out


def warmup_schedule(base_lr: float, size: Optional[int] = None,
                    warmup_epochs: float = 5.0,
                    steps_per_epoch: int = 1,
                    initial_lr_scale: Optional[float] = None) -> optax.Schedule:
    """LR warmup from lr to lr*size over warmup_epochs (reference
    LearningRateWarmupCallback: 'gradual warmup' from the one-hour
    ImageNet recipe). Compose with optax:

        optax.sgd(learning_rate=hvd.callbacks.warmup_schedule(0.1))
    """
    from .common import context as ctx_mod

    n = size if size is not None else (
        ctx_mod.size() if ctx_mod.is_initialized() else 1)
    start = base_lr * (initial_lr_scale if initial_lr_scale is not None else 1.0)
    peak = base_lr * n
    warmup_steps = max(1, int(warmup_epochs * steps_per_epoch))
    return optax.linear_schedule(start, peak, warmup_steps)


def multiplier_schedule(base_lr: float,
                        multipliers: list[tuple[int, float]],
                        steps_per_epoch: int = 1) -> optax.Schedule:
    """Piecewise-constant multiplier schedule (reference
    LearningRateScheduleCallback: multiplier per epoch range).

    ``multipliers`` = [(start_epoch, multiplier), ...] sorted ascending.
    """
    boundaries = {int(e * steps_per_epoch): m for e, m in multipliers}

    def schedule(step):
        import jax.numpy as jnp

        lr = jnp.asarray(base_lr)
        for boundary, mult in sorted(boundaries.items()):
            lr = jnp.where(step >= boundary, base_lr * mult, lr)
        return lr

    return schedule


class CommitStateCallback:
    """Commit elastic state every N batches (reference elastic
    CommitStateCallback)."""

    def __init__(self, state, batches_per_commit: int = 1):
        self.state = state
        self.n = batches_per_commit
        self._i = 0

    def __call__(self):
        self._i += 1
        if self._i % self.n == 0:
            self.state.commit()


class UpdateBatchStateCallback:
    """Track batch progress in elastic state so resumed epochs continue
    mid-epoch (reference UpdateBatchStateCallback)."""

    def __init__(self, state):
        self.state = state

    def __call__(self, batch: int):
        self.state.batch = batch

    def end_epoch(self):
        self.state.batch = 0
