"""Cross-worker synchronized BatchNorm for torch models.

Reference: /root/reference/horovod/torch/sync_batch_norm.py — batch
statistics averaged over all workers each training step, with a real
autograd Function whose backward carries the gradient terms through the
global mean/invstd (:141+). Design here: local mean / mean-of-squares are
averaged with one eager allreduce (equal per-worker batch is the
data-parallel contract, making the average of moments exact), and the
backward allreduce-averages the per-worker gradient sums the same way.

Collective names come from a deterministic per-construction counter, not
object identity: every rank must submit identical names for negotiation
to match (same-model-construction-order contract, like the reference's
call-ordered naming).
"""

from __future__ import annotations

import itertools

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu as _core

_bn_counter = itertools.count()


def _allreduce_avg_pair(a: torch.Tensor, b: torch.Tensor, name: str):
    stacked = torch.stack([a, b]).detach().cpu().numpy()
    out = np.asarray(_core.synchronize(_core.allreduce_async(
        stacked, average=True, name=name)))
    return (torch.from_numpy(np.ascontiguousarray(out[0])).to(a.dtype),
            torch.from_numpy(np.ascontiguousarray(out[1])).to(b.dtype))


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, eps, name):
        dims = [0] + list(range(2, input.dim()))
        mean = input.mean(dim=dims)
        meansq = (input * input).mean(dim=dims)
        if _core.cross_size() > 1:
            mean, meansq = _allreduce_avg_pair(mean, meansq,
                                               f"{name}.fwd_moments")
        var = (meansq - mean * mean).clamp_(min=0.0)
        invstd = torch.rsqrt(var + eps)
        shape = [1, -1] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape)) * invstd.view(shape)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape) + bias.view(shape)
        ctx.save_for_backward(input, mean, invstd, weight)
        ctx.bn_name = name
        ctx.dims = dims
        # stats are exposed only for the module's running-average update
        mean_out, var_out = mean.detach(), var.detach()
        ctx.mark_non_differentiable(mean_out, var_out)
        return out, mean_out, var_out

    @staticmethod
    def backward(ctx, dy, _dmean, _dvar):
        input, mean, invstd, weight = ctx.saved_tensors
        dims = ctx.dims
        shape = [1, -1] + [1] * (input.dim() - 2)
        xhat = (input - mean.view(shape)) * invstd.view(shape)
        # per-feature gradient sums over the *global* batch: average the
        # per-worker means (equal local counts), reference
        # sync_batch_norm.py backward's allreduce of sum_dy / sum_dy_xmu
        mean_dy = dy.mean(dim=dims)
        mean_dy_xhat = (dy * xhat).mean(dim=dims)
        if _core.cross_size() > 1:
            mean_dy, mean_dy_xhat = _allreduce_avg_pair(
                mean_dy, mean_dy_xhat, f"{ctx.bn_name}.bwd_moments")
        gx = invstd.view(shape) * (
            dy - mean_dy.view(shape) - xhat * mean_dy_xhat.view(shape))
        if weight is not None:
            gx = gx * weight.view(shape)
            # weight/bias grads stay local: the DistributedOptimizer's
            # gradient allreduce handles their reduction (reference keeps
            # the same split)
            gw = (dy * xhat).sum(dim=dims)
            gb = dy.sum(dim=dims)
        else:
            gw = gb = None
        return gx, gw, gb, None, None


class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
    """Drop-in for torch.nn.BatchNorm1d/2d/3d in data-parallel training."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_name = f"torch.sync_bn.{next(_bn_counter)}"

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(f"expected at least 2D input, got {input.dim()}D")

    def forward(self, input):
        self._check_input_dim(input)
        if not self.training:
            if self.running_mean is None:  # track_running_stats=False:
                # torch BatchNorm falls back to batch statistics in eval
                return F.batch_norm(input, None, None, self.weight,
                                    self.bias, True, 0.0, self.eps)
            return F.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, False, 0.0, self.eps)

        # torch._BatchNorm semantics: momentum=None means a cumulative
        # moving average with factor 1/num_batches_tracked
        if self.track_running_stats and self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)
            eaf = (1.0 / float(self.num_batches_tracked)
                   if self.momentum is None else self.momentum)
        else:
            eaf = 0.0 if self.momentum is None else self.momentum

        out, mean, var = _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.eps, self._hvd_name)
        if self.track_running_stats:
            n_global = (input.numel() // input.shape[1]) * max(
                _core.cross_size(), 1)
            unbiased = var * (n_global / max(n_global - 1, 1))
            with torch.no_grad():
                self.running_mean.mul_(1 - eaf).add_(mean * eaf)
                self.running_var.mul_(1 - eaf).add_(unbiased * eaf)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, module):
        """Recursively replace BatchNorm layers (torch DDP
        convert_sync_batchnorm convention)."""
        out = module
        if isinstance(module, torch.nn.modules.batchnorm._BatchNorm) and \
                not isinstance(module, cls):
            out = cls(module.num_features, module.eps, module.momentum,
                      module.affine, module.track_running_stats)
            if module.affine:
                with torch.no_grad():
                    out.weight.copy_(module.weight)
                    out.bias.copy_(module.bias)
            out.running_mean = module.running_mean
            out.running_var = module.running_var
            out.num_batches_tracked = module.num_batches_tracked
        for name, child in module.named_children():
            out.add_module(name, cls.convert_sync_batchnorm(child))
        return out
