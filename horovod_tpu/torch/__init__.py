"""horovod_tpu.torch — the PyTorch-facing API (reference horovod.torch).

Mirrors /root/reference/horovod/torch/mpi_ops.py (sync + ``*_async`` +
in-place variants, poll/synchronize handles), optimizer.py
(`DistributedOptimizer` with per-parameter gradient hooks,
``backward_passes_per_step``, ``skip_synchronize``), functions.py
(`broadcast_parameters`, `broadcast_optimizer_state`) and elastic
TorchState — implemented over the horovod_tpu eager runtime, so torch
scripts negotiate/fuse/execute through the same controller and cycle loop
as everything else. Tensors cross the boundary as host numpy (torch CPU
build; the collective itself runs on the TPU data plane).

    import horovod_tpu.torch as hvd
    hvd.init()
    optimizer = hvd.DistributedOptimizer(optimizer,
                                         named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
"""

from __future__ import annotations

import contextlib
import copy
import logging
from typing import Optional

import numpy as np
import torch

import horovod_tpu as _core
import horovod_tpu.elastic as elastic  # noqa: F401
from horovod_tpu import (  # noqa: F401  (topology + lifecycle re-exports)
    Adasum,
    Average,
    ReduceOp,
    Sum,
    cross_rank,
    cross_size,
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    is_homogeneous,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    start_timeline,
    stop_timeline,
    tpu_built,
    tpu_enabled,
    init,
    is_initialized,
    shutdown,
)


# worker-level (process) topology — reference shim semantics,
# defined once in common/worker.py
from horovod_tpu.common.worker import (  # noqa: F401
    local_rank,
    local_size,
    rank,
    size,
)
from horovod_tpu.common.exceptions import HorovodInternalError  # noqa: F401
from horovod_tpu.common.util import warn_64bit_narrowing
from horovod_tpu.elastic.state import ObjectState
from horovod_tpu.torch.elastic_sampler import ElasticSampler  # noqa: F401
from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm  # noqa: F401


class Compression:
    """fp16-on-the-wire compression (reference torch/compression.py),
    plus the blockwise-quantized wire markers (``int8``/``int4``): their
    torch-side compress/decompress is identity — the runtime compiles
    the quantization into the fused chunk programs and applies error
    feedback there (docs/performance.md, "Quantized allreduce")."""

    class none:
        @staticmethod
        def compress(t):
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t

    class fp16:
        @staticmethod
        def compress(t):
            if t.dtype in (torch.float32, torch.float64):
                return t.half(), t.dtype
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t.to(ctx) if ctx is not None else t


def _quant_markers():
    # resolved from the core module so the torch surface and the JAX
    # surface share one spec type (ops/compression.py)
    from horovod_tpu.ops.compression import Compression as _CoreCompression

    Compression.int8 = _CoreCompression.int8
    Compression.int4 = _CoreCompression.int4


_quant_markers()


# handle -> (in-place target or None, caller dtype to restore).
# JAX runs with x64 disabled (TPUs have no f64 ALUs), so float64/int64 ride
# the wire as 32-bit; the shim restores the torch dtype on the way out —
# documented precision difference vs the reference's MPI_DOUBLE path.
_handle_meta: dict[int, tuple[Optional[torch.Tensor], Optional[torch.dtype]]] = {}


LOG = logging.getLogger("horovod_tpu")


def _to_np(t: torch.Tensor) -> np.ndarray:
    if t.dtype in (torch.float64, torch.int64):
        warn_64bit_narrowing(t.dtype)
    t = t.detach().cpu()
    if t.dtype == torch.bfloat16:
        # torch cannot hand bf16 to numpy directly; reinterpret the bits
        # (torch bf16 and ml_dtypes.bfloat16 share the layout) so the
        # wire carries true bf16, not an f32 upcast
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def _np_from_wire(result, copy: bool = True) -> torch.Tensor:
    """numpy (possibly ml_dtypes.bfloat16, possibly a read-only view of
    the shared fused buffer) → torch tensor.

    ``copy=True`` hands the caller a writable copy (in-place use — grad
    mutation, zero_grad — must not corrupt fused-buffer neighbors);
    ``copy=False`` is for paths that only READ the intermediate before
    ``target.copy_``, copying just when numpy hands back a read-only
    view (from_numpy would warn)."""
    arr = np.asarray(result)
    bf16 = arr.dtype.name == "bfloat16"
    if bf16:  # torch bf16 and ml_dtypes.bfloat16 share the bit layout
        arr = arr.view(np.uint16)
    if copy or not arr.flags.writeable:
        arr = np.array(arr)
    out = torch.from_numpy(arr)
    return out.view(torch.bfloat16) if bf16 else out


def _np_to_torch(result, dtype=None) -> torch.Tensor:
    out = _np_from_wire(result)
    return out.to(dtype) if dtype is not None else out


def _result_tensor(handle: int, result) -> torch.Tensor:
    target, dtype = _handle_meta.pop(handle, (None, None))
    if target is not None:
        out = _np_from_wire(result, copy=False)
        target.copy_(out.to(target.dtype).reshape(target.shape))
        return target
    return _np_to_torch(result, dtype)


# --- async ops (reference mpi_ops.py:95-560; process_set kwarg matches
# post-v0.21 Horovod's process-set support) ----------------------------------

def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    process_set=None, compression=None) -> int:
    h = _core.allreduce_async(_to_np(tensor), average, name, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              process_set=process_set,
                              compression=compression)
    _handle_meta[h] = (None, tensor.dtype)
    return h


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0,
                     process_set=None) -> int:
    h = _core.allreduce_async(_to_np(tensor), average, name, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              process_set=process_set)
    _handle_meta[h] = (tensor, tensor.dtype)
    return h


def allgather_async(tensor, name=None, process_set=None) -> int:
    h = _core.allgather_async(_to_np(tensor), name, process_set=process_set)
    _handle_meta[h] = (None, tensor.dtype)
    return h


def broadcast_async(tensor, root_rank, name=None, process_set=None) -> int:
    h = _core.broadcast_async(_to_np(tensor), root_rank, name,
                              process_set=process_set)
    _handle_meta[h] = (None, tensor.dtype)
    return h


def broadcast_async_(tensor, root_rank, name=None, process_set=None) -> int:
    h = _core.broadcast_async(_to_np(tensor), root_rank, name,
                              process_set=process_set)
    _handle_meta[h] = (tensor, tensor.dtype)
    return h


def alltoall_async(tensor, splits=None, name=None, process_set=None) -> int:
    h = _core.alltoall_async(_to_np(tensor),
                             None if splits is None else _to_np(splits), name,
                             process_set=process_set)
    _handle_meta[h] = (None, tensor.dtype)
    return h


def reducescatter_async(tensor, name=None, op=None, process_set=None) -> int:
    """Reduce-scatter along dim 0 (reference torch/mpi_ops.py reducescatter
    in post-v0.21 releases)."""
    h = _core.reducescatter_async(_to_np(tensor), name, op=op,
                                  process_set=process_set)
    _handle_meta[h] = (None, tensor.dtype)
    return h


import itertools

_group_counter = itertools.count()


def _group_base(name):
    # unique per unnamed call (reference "grouped_allreduce.noname.<n>"):
    # concurrent unnamed groups must not collide on in-flight names
    return name or f"grouped_allreduce.noname.{next(_group_counter)}"


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set=None) -> list:
    """One logical fused op over a list (reference torch/mpi_ops.py:345):
    the cycle loop fuses the group into a single flat collective."""
    base = _group_base(name)
    return [allreduce_async(t, average, f"{base}.{i}", op,
                            prescale_factor, postscale_factor, process_set)
            for i, t in enumerate(tensors)]


def grouped_allreduce_async_(tensors, average=None, name=None, op=None,
                             prescale_factor=1.0, postscale_factor=1.0,
                             process_set=None) -> list:
    """In-place grouped variant (reference torch/mpi_ops.py:444)."""
    base = _group_base(name)
    return [allreduce_async_(t, average, f"{base}.{i}", op,
                             prescale_factor, postscale_factor, process_set)
            for i, t in enumerate(tensors)]


def poll(handle: int) -> bool:
    return _core.poll(handle)


def synchronize(handle: int):
    try:
        result = _core.synchronize(handle)
    except Exception:
        # drop the meta entry even on failure (elastic reset raises
        # HorovodInternalError for every in-flight handle) so in-place
        # targets aren't pinned forever
        _handle_meta.pop(handle, None)
        raise
    if isinstance(result, tuple):  # alltoall returns (output, recv_splits)
        out, splits = result
        _, dtype = _handle_meta.pop(handle, (None, None))
        return _np_to_torch(out, dtype), _np_to_torch(splits)
    return _result_tensor(handle, result)


# --- differentiable sync ops ------------------------------------------------
# The reference's sync collectives are autograd ops (torch/mpi_ops.py
# HorovodAllreduce/HorovodAllgather/HorovodBroadcast/HorovodAlltoall
# Function subclasses): hvd.allreduce(x) inside an autograd graph
# backpropagates a collective of the cotangent. Same gradient math as
# this repo's TF shim (tensorflow/__init__.py), so the two frameworks
# agree: allreduce -> allreduce with the same op; allgather ->
# allreduce-average then this worker's row slice; broadcast ->
# allreduce-average at the root, zeros elsewhere; alltoall -> alltoall
# routed back with splits = received_splits.

def _grad_wanted(tensor) -> bool:
    return torch.is_grad_enabled() and tensor.requires_grad


class _AllreduceOp(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name, op, prescale, postscale, ps):
        ctx.meta = (average, name, op, prescale, postscale, ps)
        return synchronize(allreduce_async(tensor, average, name, op,
                                           prescale, postscale, ps))

    @staticmethod
    def backward(ctx, dy):
        average, name, op, prescale, postscale, ps = ctx.meta
        red = allreduce(dy, average=average,
                        name=f"{name}.grad" if name else None, op=op,
                        prescale_factor=prescale, postscale_factor=postscale,
                        process_set=ps)
        return red, None, None, None, None, None, None


class _AllgatherOp(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name, ps):
        ctx.meta = (name, ps, int(tensor.shape[0]) if tensor.dim() else 0)
        return synchronize(allgather_async(tensor, name, ps))

    @staticmethod
    def backward(ctx, dy):
        name, ps, local_rows = ctx.meta
        red = allreduce(dy, average=True,
                        name=f"{name}.grad" if name else None,
                        process_set=ps)
        pset = ps or _core.global_process_set()
        if pset.cross_size <= 1:
            start = 0
        else:
            # ragged inputs: one backward-only exchange of row counts
            sizes = _core.synchronize(_core.allgather_async(
                np.asarray([local_rows]),
                f"{name or 'allgather'}.grad.sizes", process_set=ps))
            start = int(np.sum(np.asarray(sizes)[:pset.cross_rank]))
        return red[start:start + local_rows], None, None


class _BroadcastOp(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name, ps):
        ctx.meta = (root_rank, name, ps)
        return synchronize(broadcast_async(tensor, root_rank, name, ps))

    @staticmethod
    def backward(ctx, dy):
        root_rank, name, ps = ctx.meta
        red = allreduce(dy, average=True,
                        name=f"{name}.grad" if name else None,
                        process_set=ps)
        import jax

        pset = ps or _core.global_process_set()
        is_root = (pset.devices[root_rank].process_index
                   == jax.process_index())
        return (red if is_root else red * 0), None, None, None


class _AlltoallOp(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, splits, name, ps):
        out, recv = synchronize(alltoall_async(tensor, splits, name, ps))
        ctx.meta = (name, ps)
        ctx.recv = recv
        ctx.mark_non_differentiable(recv)
        return out, recv

    @staticmethod
    def backward(ctx, dy, _drecv=None):
        name, ps = ctx.meta
        back, _ = alltoall(dy.contiguous(), splits=ctx.recv,
                           name=f"{name}.grad" if name else None,
                           process_set=ps)
        return back, None, None, None


class _GroupedAllreduceOp(torch.autograd.Function):
    """Differentiable grouped allreduce (reference mpi_ops.py grouped
    gradient registration): the backward grouped-allreduces all
    cotangents as one fused batch, like the forward."""

    @staticmethod
    def forward(ctx, average, name, op, prescale, postscale, ps, *tensors):
        ctx.meta = (average, name, op, prescale, postscale, ps)
        hs = grouped_allreduce_async(list(tensors), average, name, op,
                                    prescale, postscale, ps)
        return tuple(synchronize(h) for h in hs)

    @staticmethod
    def backward(ctx, *dys):
        average, name, op, prescale, postscale, ps = ctx.meta
        red = grouped_allreduce(
            [d.contiguous() for d in dys], average=average,
            name=f"{name}.grad" if name else None, op=op,
            prescale_factor=prescale, postscale_factor=postscale,
            process_set=ps)
        return (None,) * 6 + tuple(red)


# --- sync wrappers ----------------------------------------------------------

def allreduce(tensor, average=None, name=None, op=None,
              compression=Compression.none,
              prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    t, ctx = compression.compress(tensor)
    # quant markers ride to the runtime as the wire format; compress()
    # above was identity for them (autograd-tracked tensors keep the
    # uncompressed wire — the backward collective has no marker to match)
    qm = (compression if getattr(compression, "quant_spec", None)
          is not None else None)
    if _grad_wanted(t):
        out = _AllreduceOp.apply(t, average, name, op, prescale_factor,
                                 postscale_factor, process_set)
    else:
        out = synchronize(allreduce_async(t, average, name, op,
                                          prescale_factor, postscale_factor,
                                          process_set, compression=qm))
    return compression.decompress(out, ctx)


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    return synchronize(allreduce_async_(tensor, average, name, op,
                                        prescale_factor, postscale_factor,
                                        process_set))


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      compression=Compression.none,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None):
    comp = [compression.compress(t) for t in tensors]
    if any(_grad_wanted(c[0]) for c in comp):
        outs = _GroupedAllreduceOp.apply(
            average, name, op, prescale_factor, postscale_factor,
            process_set, *[c[0] for c in comp])
        return [compression.decompress(o, c[1])
                for o, c in zip(outs, comp)]
    hs = grouped_allreduce_async([c[0] for c in comp], average, name, op,
                                 prescale_factor, postscale_factor,
                                 process_set)
    return [compression.decompress(synchronize(h), c[1])
            for h, c in zip(hs, comp)]


def grouped_allreduce_(tensors, average=None, name=None, op=None,
                       prescale_factor=1.0, postscale_factor=1.0,
                       process_set=None):
    hs = grouped_allreduce_async_(tensors, average, name, op,
                                  prescale_factor, postscale_factor,
                                  process_set)
    return [synchronize(h) for h in hs]


def allgather(tensor, name=None, process_set=None):
    if _grad_wanted(tensor):
        return _AllgatherOp.apply(tensor, name, process_set)
    return synchronize(allgather_async(tensor, name, process_set))


def broadcast(tensor, root_rank, name=None, process_set=None):
    if _grad_wanted(tensor):
        return _BroadcastOp.apply(tensor, root_rank, name, process_set)
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


def broadcast_(tensor, root_rank, name=None, process_set=None):
    return synchronize(broadcast_async_(tensor, root_rank, name, process_set))


def alltoall(tensor, splits=None, name=None, process_set=None):
    if _grad_wanted(tensor):
        return _AlltoallOp.apply(tensor, splits, name, process_set)
    return synchronize(alltoall_async(tensor, splits, name, process_set))


def reducescatter(tensor, name=None, op=None, process_set=None):
    return synchronize(reducescatter_async(tensor, name, op, process_set))


def sparse_allreduce_async(tensor, name, op=Average,
                           prescale_factor=1.0, postscale_factor=1.0,
                           process_set=None):
    """Sparse COO reduction via allgather of values+indices (reference
    torch/mpi_ops.py:512). Returns a thunk that completes the op.
    ``prescale_factor``/``postscale_factor`` scale the values around the
    gather-sum, mirroring the dense allreduce's factors (the allgather +
    coalesce IS the sum, so pre/post placement is equivalent up to
    rounding, as in the dense path)."""
    t = tensor.coalesce()
    values = t.values()
    if prescale_factor != 1.0:
        values = values * prescale_factor
    hi = allgather_async(t.indices().t().contiguous(), f"{name}.indices",
                         process_set=process_set)
    hv = allgather_async(values, f"{name}.values", process_set=process_set)

    def finish():
        indices = synchronize(hi).t()
        values = synchronize(hv)
        if postscale_factor != 1.0:
            values = values * postscale_factor
        if op == Average:
            # eager collectives contribute per *process* (cross_size), not
            # per chip — divide by the actual number of contributors
            n = (process_set.cross_size if process_set is not None
                 else cross_size())
            values = values / n
        return torch.sparse_coo_tensor(indices, values, t.shape).coalesce()

    return finish


def join() -> int:
    return _core.join()


def barrier():
    _core.barrier()


# --- parameter/optimizer broadcast (reference torch/functions.py) -----------

def broadcast_parameters(params, root_rank: int = 0):
    """Accepts a state_dict or an iterable of (name, tensor)
    (reference functions.py:29)."""
    items = sorted(params.items()) if isinstance(params, dict) \
        else sorted(dict(params).items())
    handles = [broadcast_async_(p.data, root_rank, f"bcast.{name}")
               for name, p in items if isinstance(p, torch.Tensor)]
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0):
    """Broadcast full optimizer state from root (reference
    functions.py:61; pickle path covers non-tensor entries)."""
    state = _core.broadcast_object(optimizer.state_dict(), root_rank=root_rank)
    optimizer.load_state_dict(state)


def broadcast_object(obj, root_rank: int = 0, name=None):
    return _core.broadcast_object(obj, root_rank=root_rank)


def allgather_object(obj, name=None):
    return _core.allgather_object(obj)


# --- DistributedOptimizer (reference torch/optimizer.py) --------------------

class _DistributedMixin:
    """Methods grafted onto the wrapped optimizer's own class: per-parameter
    post-accumulate hooks launch async allreduces, step() synchronizes
    (reference optimizer.py:35, hooks :219-247, synchronize :249-286).
    The reference dynamically subclasses the wrapped optimizer's class so
    isinstance-based integrations (LR schedulers, GradScaler, Lightning)
    accept the result; we do the same by swapping ``__class__`` in place,
    which additionally preserves existing optimizer state."""

    def _hvd_setup(self, named_parameters, compression, op,
                   backward_passes_per_step, prescale_factor,
                   postscale_factor, gradient_predivide_factor=1.0,
                   sparse_as_dense=False, process_set=None):
        self._process_set = process_set
        if gradient_predivide_factor != 1.0:
            if op != Average:
                # reference optimizer.py:76: predivide splits an Average
                # into Sum with pre/postscale — meaningless for other ops
                raise ValueError(
                    "gradient_predivide_factor requires op=Average")
            # sum with prescale 1/f, postscale f/n == average, but lets the
            # user pick where the division happens for numerics
            op = Sum
            prescale_factor = prescale_factor / gradient_predivide_factor
            n = (process_set.cross_size if process_set is not None
                 else max(cross_size(), 1))
            postscale_factor = (postscale_factor * gradient_predivide_factor
                                / max(n, 1))
        self._compression = compression
        self._op = op
        self._bpps = backward_passes_per_step
        self._prescale = prescale_factor
        self._postscale = postscale_factor
        self._sparse_as_dense = sparse_as_dense
        self._sparse_thunks: dict[torch.Tensor, object] = {}
        self._handles: dict[torch.Tensor, tuple[int, object]] = {}
        self._passes: dict[torch.Tensor, int] = {}
        self._should_sync = True
        self._hook_handles = []
        self._names = names = _build_param_names(
            self, named_parameters, "allreduce")
        for p in names:
            if p.requires_grad:
                self._passes[p] = 0
                self._hook_handles.append(
                    p.register_post_accumulate_grad_hook(self._hook))

    # hook fired when a parameter's gradient is fully accumulated.
    # With backward_passes_per_step > 1 the *accumulated sum* is allreduced
    # unscaled, matching the reference semantics (optimizer.py:219-247).
    def _hook(self, p):
        self._passes[p] += 1
        if self._passes[p] < self._bpps:
            return
        self._passes[p] = 0
        self._launch_reduce(p, p.grad)

    def _launch_reduce(self, p, grad):
        if grad.is_sparse:
            if self._sparse_as_dense:
                # reference optimizer.py: densify before the wire
                grad = grad.to_dense()
            else:
                # reference _sparse_allreduce_grad_async: COO values +
                # indices ride an allgather; completed in synchronize().
                # The dense path's pre/postscale factors (incl. the
                # predivide rewrite) apply to the values identically.
                self._sparse_thunks[p] = sparse_allreduce_async(
                    grad, name=self._names[p], op=self._op,
                    prescale_factor=self._prescale,
                    postscale_factor=self._postscale,
                    process_set=self._process_set)
                return
        comp, ctx = self._compression.compress(grad)
        qm = (self._compression
              if getattr(self._compression, "quant_spec", None) is not None
              else None)
        h = allreduce_async(comp, name=self._names[p], op=self._op,
                            prescale_factor=self._prescale,
                            postscale_factor=self._postscale,
                            process_set=self._process_set, compression=qm)
        self._handles[p] = (h, ctx)

    def synchronize(self):
        # Reference optimizer.py synchronize(): every tracked param without
        # a pending handle gets an allreduce now — hooks that never fired
        # (dynamically-unused params) contribute zeros, so all ranks submit
        # the same collective set and the negotiation can't mismatch/hang —
        # and accumulation counters reset so a mid-window step() doesn't
        # leave stale pass counts.
        for p, name in self._names.items():
            if (not p.requires_grad or p in self._handles
                    or p in self._sparse_thunks):
                continue
            if p.grad is None:
                p.grad = torch.zeros_like(p)
            # mid-window sparse grads (bpps>1) take the same sparse route
            # as the hook — the dense fallback cannot convert COO and
            # would submit a different collective set than peer ranks
            self._launch_reduce(p, p.grad)
        for p in self._passes:
            self._passes[p] = 0
        for p, (h, ctx) in list(self._handles.items()):
            reduced = synchronize(h)
            p.grad = self._compression.decompress(
                reduced, ctx).reshape(p.grad.shape).to(p.grad.dtype)
        self._handles.clear()
        for p, finish in list(self._sparse_thunks.items()):
            p.grad = finish().to(p.grad.dtype)
        self._sparse_thunks.clear()

    def set_backward_passes_per_step(self, passes: int):
        """Change the local gradient-accumulation window (reference
        optimizer.py set_backward_passes_per_step); resets pass counters."""
        self._bpps = int(passes)
        for p in self._passes:
            self._passes[p] = 0

    @contextlib.contextmanager
    def skip_synchronize(self):
        """reference optimizer.py skip_synchronize: suppress the implicit
        synchronize in the next step() (used with gradient clipping after a
        manual synchronize())."""
        self._should_sync = False
        try:
            yield
        finally:
            self._should_sync = True

    def step(self, closure=None):
        if self._should_sync:
            self.synchronize()
        return self._hvd_base.step(self, closure)


def _build_param_names(optimizer, named_parameters, noname_prefix):
    """Shared name validation (reference optimizer.py find_duplicates +
    unnamed-params check): duplicates would issue collectives under one
    negotiation name and mis-fuse across ranks; uncovered params would
    silently never reduce (or, in the Adasum path, never step)."""
    if named_parameters is not None:
        seen, dups = set(), set()
        for n, _ in named_parameters:
            if n in seen:
                dups.add(n)
            seen.add(n)
        if dups:
            raise ValueError(
                "named_parameters contains duplicate names: "
                f"{sorted(dups)}")
        names = {p: n for n, p in named_parameters}
        all_params = {p for g in optimizer.param_groups for p in g["params"]}
        missing = all_params - names.keys()
        if missing:
            raise ValueError(
                "named_parameters does not cover all optimizer "
                f"parameters ({len(missing)} uncovered)")
        return names
    names = {}
    for gi, group in enumerate(optimizer.param_groups):
        for pi, p in enumerate(group["params"]):
            names[p] = f"{noname_prefix}.noname.{gi}.{pi}"
    return names


class _ShardedMixin:
    """ZeRO-1 for the torch shim (overlaid on ``_DistributedMixin``):
    gradients hook-allreduce exactly as in the plain wrapper, but each
    parameter's *optimizer step* runs on a single owning rank and the
    updated parameter is broadcast from its owner. torch optimizers
    cannot slice one tensor's step across ranks, so ownership is
    whole-leaf (``parallel/sharding_policy.assign_owners``: greedy
    largest-first balance; leaves under the replicate threshold step on
    every rank with no broadcast). torch materializes per-param state
    lazily on first step, so each rank only ever allocates state for
    the params it owns plus the replicated ones — the ~1/N ZeRO-1
    state footprint, with no state-dict surgery.

    Caveats (docs/sharded_optimizer.md, "torch mode"): ``state_dict()``
    holds only this rank's shard of optimizer state — gather before
    checkpointing or save per-rank. After an elastic resize the owner
    table is rebuilt deterministically from the new world, but state
    for reassigned params is re-created fresh by torch (momentum for
    those leaves restarts); the JAX engine re-materializes instead."""

    def _hvd_sharded_setup(self, min_shard_elems):
        from horovod_tpu.opt.sharded import _resolve_min_shard_elems
        from horovod_tpu.utils import metrics as _metrics

        self._sharded_min_elems = _resolve_min_shard_elems(min_shard_elems)
        reg = _metrics.get_registry()
        wire = "hvd_sharded_update_wire_bytes_total"
        wire_help = ("sharded-update wire bytes by phase (ring accounting: "
                     "(N-1)/N of the buffer per RS or AG pass)")
        self._m_bcast = reg.counter(wire, wire_help, phase="broadcast")
        self._m_frac = reg.gauge(
            "hvd_sharded_update_shard_fraction",
            "fraction of elements on the sharded path (rest replicate)")
        self._sharded_gen = None
        self._hvd_build_owners()

    def _hvd_build_owners(self):
        from horovod_tpu.common import env as env_schema
        from horovod_tpu.parallel.sharding_policy import assign_owners
        from horovod_tpu.utils import flightrec

        ps = self._process_set or _core.global_process_set()
        ws = max(ps.cross_size, 1)
        rk = ps.cross_rank
        # param_groups order is the deterministic leaf order — identical
        # on every rank the same way _build_param_names relies on it
        params = [p for g in self.param_groups for p in g["params"]]
        sizes = [p.numel() for p in params]
        owner_list = assign_owners(sizes, ws,
                                   min_shard_elems=self._sharded_min_elems)
        self._sharded_world = ws
        self._sharded_rank = rk
        self._owners = dict(zip(params, owner_list))
        # broadcast root_rank is a chip index: pick each owning
        # process's first member chip
        self._owner_chip = {
            r: next(i for i, d in enumerate(ps.devices)
                    if d.process_index == ps._proc_indices[r])
            for r in range(ws)}
        self._sharded_gen = env_schema.get_int(env_schema.HOROVOD_ELASTIC_GEN,
                                               0)
        owned = sum(s for s, o in zip(sizes, owner_list) if o is not None)
        total = max(sum(sizes), 1)
        self._m_frac.set(owned / total)
        flightrec.note("reshard", generation=self._sharded_gen, world=ws,
                       rank=rk, mode="torch-whole-leaf",
                       owned_leaves=sum(o is not None for o in owner_list),
                       replicated_leaves=sum(o is None for o in owner_list))

    def step(self, closure=None):
        from horovod_tpu.common import env as env_schema

        if self._should_sync:
            self.synchronize()
        if self._sharded_gen != env_schema.get_int(
                env_schema.HOROVOD_ELASTIC_GEN, 0):
            # elastic resize: every rank recomputes the same owner table
            # from the new world without communicating
            self._hvd_build_owners()
        stashed = []
        for group in self.param_groups:
            stashed.append(group["params"])
            group["params"] = [
                p for p in group["params"]
                if self._owners.get(p, None) in (None, self._sharded_rank)]
        try:
            loss = self._hvd_base.step(self, closure)
        finally:
            for params, group in zip(stashed, self.param_groups):
                group["params"] = params
        self._hvd_broadcast_owned()
        return loss

    def _hvd_broadcast_owned(self):
        if self._sharded_world <= 1:
            return
        handles = []
        nbytes = 0
        for p, owner in self._owners.items():
            if owner is None:
                continue
            handles.append(broadcast_async_(
                p.data, self._owner_chip[owner],
                f"sharded.{self._names[p]}",
                process_set=self._process_set))
            nbytes += p.numel() * p.element_size()
        for h in handles:
            synchronize(h)
        w = self._sharded_world
        self._m_bcast.inc(int(nbytes * (w - 1) / w))


class _AdasumMixin:
    """Delta-Adasum optimizer (reference torch/optimizer.py:329
    _DistributedAdasumOptimizer): each parameter's hook runs the LOCAL
    base-optimizer step for that parameter immediately, forming
    delta = p_after_step - p_before_step; deltas are combined across
    workers with the scale-invariant Adasum reduction and committed as
    p = start + adasum(delta). Same model-combining semantics as this
    repo's TF DistributedAdasumOptimizer."""

    def _hvd_adasum_setup(self, named_parameters, compression,
                          backward_passes_per_step):
        self._compression = compression
        self._bpps = int(backward_passes_per_step)
        self._passes: dict[torch.Tensor, int] = {}
        self._handles: dict[torch.Tensor, tuple] = {}
        self._starts: dict[torch.Tensor, torch.Tensor] = {}
        self._hook_handles = []
        self._names = _build_param_names(self, named_parameters, "adasum")
        for p in self._names:
            if p.requires_grad:
                self._passes[p] = 0
                self._starts[p] = torch.zeros_like(p.data)
                self._hook_handles.append(
                    p.register_post_accumulate_grad_hook(self._hvd_delta_hook))

    def _hvd_local_step_delta(self, p):
        """Run the base optimizer on ONLY this param, then turn p into the
        delta (reference _allreduce_grad_async, optimizer.py:397-439)."""
        start = self._starts[p]
        start.copy_(p.data)
        stashed = []
        for group in self.param_groups:
            stashed.append(group["params"])
            group["params"] = [p] if any(p is v for v in group["params"]) \
                else []
        try:
            self._hvd_base.step(self)
        finally:
            for params, group in zip(stashed, self.param_groups):
                group["params"] = params
        p.data.sub_(start)  # p now holds delta = -alpha * f(g)
        comp, ctx = self._compression.compress(p.data)
        h = allreduce_async(comp, name=self._names[p], op=Adasum)
        self._handles[p] = (h, ctx)

    def _hvd_delta_hook(self, p):
        self._passes[p] += 1
        if self._passes[p] < self._bpps:
            return
        self._passes[p] = 0
        self._hvd_local_step_delta(p)

    def synchronize(self):
        """Reference optimizer.py:460: a separate synchronize is
        meaningless for the delta optimizer (step commits)."""

    @contextlib.contextmanager
    def skip_synchronize(self):
        raise AssertionError(
            "Skipping synchronization is not supported when using Adasum "
            "optimizer.")

    def set_backward_passes_per_step(self, passes: int):
        self._bpps = int(passes)
        for p in self._passes:
            self._passes[p] = 0

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        # symmetric collective set: params whose hook did not fire this
        # step contribute a zero delta (Adasum of a zero vector adds
        # nothing but keeps all ranks' submissions aligned)
        for p in self._names:
            if p.requires_grad and p not in self._handles:
                self._hvd_local_step_delta(p) if p.grad is not None \
                    else self._hvd_zero_delta(p)
        for p, (h, ctx) in list(self._handles.items()):
            reduced = synchronize(h)
            delta = self._compression.decompress(reduced, ctx) \
                .reshape(p.data.shape).to(p.data.dtype)
            p.data.copy_(self._starts[p] + delta)
        self._handles.clear()
        for p in self._passes:
            self._passes[p] = 0
        return loss

    def _hvd_zero_delta(self, p):
        start = self._starts[p]
        start.copy_(p.data)
        p.data.zero_()
        comp, ctx = self._compression.compress(p.data)
        h = allreduce_async(comp, name=self._names[p], op=Adasum)
        self._handles[p] = (h, ctx)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         op=Average,
                         backward_passes_per_step: int = 1,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         gradient_predivide_factor: float = 1.0,
                         sparse_as_dense: bool = False,
                         process_set=None,
                         sharded_update: Optional[bool] = None,
                         min_shard_elems: Optional[int] = None):
    if hasattr(optimizer, "_hvd_base"):
        # Re-wrapping would make the grafted step() re-enter itself through
        # the newest swapped class (infinite recursion) and register every
        # hook twice.
        raise ValueError(
            "optimizer is already wrapped by DistributedOptimizer")
    if sharded_update is None:
        from horovod_tpu.opt.sharded import sharded_update_enabled
        sharded_update = sharded_update_enabled()
    base = optimizer.__class__
    if op == Adasum and cross_size() > 1:
        if sharded_update:
            # Adasum combines *models* (per-param local step + scale-
            # invariant delta reduction) — there is no shared optimizer
            # step to shard
            raise ValueError("sharded_update is not supported with op=Adasum")
        # reference optimizer.py:576: Adasum selects the delta optimizer
        # (size()==1 degenerates to the regular wrapper there and here)
        if (gradient_predivide_factor != 1.0 or prescale_factor != 1.0
                or postscale_factor != 1.0 or sparse_as_dense):
            raise ValueError(
                "gradient_predivide_factor/prescale/postscale/"
                "sparse_as_dense are not supported with op=Adasum")
        body = {k: v for k, v in _AdasumMixin.__dict__.items()
                if not k.startswith("__")}
        body["_hvd_base"] = base
        optimizer.__class__ = type("DistributedAdasum" + base.__name__,
                                   (base,), body)
        optimizer._hvd_adasum_setup(
            list(named_parameters) if named_parameters is not None else None,
            compression, backward_passes_per_step)
        return optimizer
    body = {k: v for k, v in _DistributedMixin.__dict__.items()
            if not k.startswith("__")}
    cls_prefix = "Distributed"
    if sharded_update:
        # overlay: keeps the hook/synchronize machinery, replaces step()
        # with the owner-restricted step + owner broadcast
        body.update({k: v for k, v in _ShardedMixin.__dict__.items()
                     if not k.startswith("__")})
        cls_prefix = "ShardedDistributed"
    body["_hvd_base"] = base
    optimizer.__class__ = type(cls_prefix + base.__name__, (base,), body)
    optimizer._hvd_setup(
        list(named_parameters) if named_parameters is not None else None,
        compression, op, backward_passes_per_step,
        prescale_factor, postscale_factor, gradient_predivide_factor,
        sparse_as_dense, process_set)
    if sharded_update:
        optimizer._hvd_sharded_setup(min_shard_elems)
    return optimizer


# --- elastic TorchState (reference torch/elastic/state.py) ------------------

class TorchState(ObjectState):
    """Elastic state with torch model/optimizer/sampler handlers: snapshots
    are cpu clones of state_dicts; sync broadcasts from rank 0 (and, for
    the sampler, merges every worker's processed-index set — reference
    torch/elastic/state.py SamplerStateHandler)."""

    def __init__(self, model=None, optimizer=None, sampler=None, **kwargs):
        self._model = model
        self._optimizer = optimizer
        self._sampler = sampler
        self._model_saved = None
        self._opt_saved = None
        self._sampler_saved = None
        super().__init__(**kwargs)

    # public handles (reference TorchState: verbatim scripts drive
    # state.model / state.optimizer / state.sampler directly, and may
    # REASSIGN them after a reset) — property-backed so a reassignment
    # stays attached to save/restore/sync instead of silently training
    # an object the snapshots never see
    @property
    def model(self):
        return self._model

    @model.setter
    def model(self, m):
        self._model = m

    @property
    def optimizer(self):
        return self._optimizer

    @optimizer.setter
    def optimizer(self, o):
        self._optimizer = o

    @property
    def sampler(self):
        return self._sampler

    @sampler.setter
    def sampler(self, s):
        self._sampler = s

    def save(self):
        if self._model is not None:
            self._model_saved = {k: v.detach().clone()
                                 for k, v in self._model.state_dict().items()}
        if self._optimizer is not None:
            self._opt_saved = copy.deepcopy(self._optimizer.state_dict())
        if self._sampler is not None:
            self._sampler_saved = copy.deepcopy(self._sampler.state_dict())
        super().save()

    def restore(self):
        if self._model_saved is not None:
            self._model.load_state_dict(self._model_saved)
        if self._opt_saved is not None:
            self._optimizer.load_state_dict(self._opt_saved)
        if self._sampler_saved is not None:
            self._sampler.load_state_dict(self._sampler_saved)
        super().restore()

    def sync(self):
        if self._model is not None:
            broadcast_parameters(self._model.state_dict(), root_rank=0)
        if self._optimizer is not None:
            broadcast_optimizer_state(self._optimizer, root_rank=0)
        if self._sampler is not None:
            # after a resize no single worker knows the full progress:
            # union everyone's processed indices, then re-shard
            st = self._sampler.state_dict()
            all_states = allgather_object(st)
            merged = set()
            for s in all_states if isinstance(all_states, list) else [st]:
                merged.update(s.get("processed_indices", ()))
            st["processed_indices"] = sorted(merged)
            self._sampler.load_state_dict(st)
        super().sync()


# hvd.elastic under the torch namespace carries the torch-specific state
# classes too (reference horovod/torch/elastic/__init__.py exposes
# TorchState + ElasticSampler next to run): a verbatim
# `hvd.elastic.TorchState(model, optimizer, ...)` must resolve. Built as
# a namespace copy so the shared horovod_tpu.elastic module stays
# framework-neutral.
from horovod_tpu.common.util import module_namespace as _module_ns  # noqa: E402

elastic = _module_ns(elastic, TorchState=TorchState,
                     ElasticSampler=ElasticSampler)
