"""Elastic-aware distributed sampler for torch DataLoaders.

Reference: /root/reference/horovod/torch/elastic/sampler.py —
`ElasticSampler` shards the dataset across workers and tracks *processed*
indices so that, after an elastic reset mid-epoch (world resize or
failure recovery), surviving data is re-sharded over the new world and
already-processed samples are not repeated.
"""

from __future__ import annotations

import math
import random
from typing import Iterator, Optional

import torch.utils.data

import horovod_tpu as _core


class ElasticSampler(torch.utils.data.Sampler):
    def __init__(self, dataset, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_indices: set[int] = set()
        self.num_replicas = 1
        self.rank = 0
        self.remaining_indices: list[int] = []
        self.num_samples = 0
        self.total_size = 0
        self.indices: list[int] = []
        self.reset()

    # -- epoch / progress tracking ------------------------------------------
    def set_epoch(self, epoch: int):
        """New epoch: clear progress and re-shard (reference set_epoch)."""
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int):
        """Mark one local batch as processed."""
        self.record_indices(self.get_indices(batch_idx, batch_size))

    def record_indices(self, indices):
        self.processed_indices.update(indices)

    def get_indices(self, batch_idx: int, batch_size: int) -> list[int]:
        start = batch_idx * batch_size
        return self.indices[start:start + batch_size]

    # -- elastic reset -------------------------------------------------------
    def reset(self):
        """Re-shard the *unprocessed* remainder over the current world
        (called by set_epoch, and by TorchState on elastic reset)."""
        # worker == process (the torch shim's data-parallel unit)
        self.num_replicas = max(_core.cross_size(), 1)
        self.rank = _core.cross_rank() if self.num_replicas > 1 else 0

        remaining = [i for i in range(len(self.dataset))
                     if i not in self.processed_indices]
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(remaining)
        self.remaining_indices = remaining

        self.num_samples = int(
            math.ceil(len(remaining) / float(self.num_replicas)))
        self.total_size = self.num_samples * self.num_replicas
        # pad to equal per-worker length (torch DistributedSampler
        # convention; keeps collective step counts aligned)
        padded = list(remaining)
        if padded:
            while len(padded) < self.total_size:
                padded += padded[:self.total_size - len(padded)]
        self.indices = padded[self.rank:self.total_size:self.num_replicas]

    # -- Sampler protocol ----------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        self.reset()
        return iter(self.indices)

    def __len__(self) -> int:
        return self.num_samples

    # -- elastic state (consumed by TorchState's sampler handling) ----------
    def state_dict(self) -> dict:
        return {"epoch": self.epoch,
                "processed_indices": sorted(self.processed_indices)}

    def load_state_dict(self, state: dict):
        self.epoch = int(state.get("epoch", 0))
        self.processed_indices = set(state.get("processed_indices", ()))
        self.reset()
