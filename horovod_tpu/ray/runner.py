"""RayExecutor implementation. Reference: /root/reference/horovod/ray/
runner.py — RayExecutor (:248), Coordinator (:176), NodeColocator (:100).

Original TPU-native design: the executor asks an *engine* for worker
handles, registers their hostnames with the `Coordinator` (which computes
the same rank/local_rank/cross_rank topology the reference derives), then
pushes env vars + the rendezvous address and invokes the user function
everywhere.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from collections import defaultdict
from typing import Any, Callable, Optional

from ..common import env as env_schema
from ..runner.http_server import RendezvousServer


from ..elastic.executor import _serializer  # noqa: E402  (shared helper)


class Coordinator:
    """Computes per-rank topology env from worker registrations (reference
    ray/runner.py:176). Ranks are assigned per registration order; workers
    on the same hostname form a local group."""

    def __init__(self):
        self._by_host: dict[str, list[int]] = defaultdict(list)

    def register(self, hostname: str, world_rank: int):
        self._by_host[hostname].append(world_rank)

    @property
    def world_size(self) -> int:
        return sum(len(v) for v in self._by_host.values())

    @property
    def hoststring(self) -> str:
        return ",".join(f"{h}:{len(r)}" for h, r in self._by_host.items())

    def rank_envs(self) -> dict[int, dict[str, str]]:
        """world_rank → {HOROVOD_RANK, LOCAL_RANK/SIZE, CROSS_RANK/SIZE}."""
        out: dict[int, dict[str, str]] = {}
        n = self.world_size
        for cross_rank, (host, ranks) in enumerate(self._by_host.items()):
            for local_rank, world_rank in enumerate(sorted(ranks)):
                out[world_rank] = {
                    env_schema.HOROVOD_RANK: str(world_rank),
                    env_schema.HOROVOD_SIZE: str(n),
                    env_schema.HOROVOD_LOCAL_RANK: str(local_rank),
                    env_schema.HOROVOD_LOCAL_SIZE: str(len(ranks)),
                    env_schema.HOROVOD_CROSS_RANK: str(cross_rank),
                    env_schema.HOROVOD_CROSS_SIZE: str(len(self._by_host)),
                    env_schema.HOROVOD_HOSTNAME: host,
                }
        return out


class LocalProcessEngine:
    """Hermetic engine: one subprocess per worker on this machine. Used by
    tests and as a no-cluster fallback; also the shape a future TPU-pod
    engine plugs into (one process per host, chips via jax)."""

    def __init__(self):
        self._envs: dict[int, dict[str, str]] = {}
        self._n = 0

    def start(self, num_workers: int, envs: dict[int, dict[str, str]]):
        self._n = num_workers
        self._envs = envs

    def hostnames(self, num_workers: int) -> list[str]:
        import socket

        return [socket.gethostname()] * num_workers

    def free_port_on(self, hostname: str) -> int:
        from ..runner.launch import _free_port

        return _free_port()  # all workers are local: a local probe is exact

    def run(self, fn: Callable, args: tuple, kwargs: dict) -> list:
        workdir = tempfile.mkdtemp(prefix="hvd_ray_local_")
        payload = os.path.join(workdir, "fn.pkl")
        with open(payload, "wb") as f:
            _serializer().dump((fn, args, kwargs), f)
        # the child must resolve fn's defining module (plain pickle stores
        # a module reference, not code) — ship the parent's import paths
        parent_path = list(sys.path)
        procs = []
        for rank in range(self._n):
            env = dict(os.environ)
            env.update(self._envs.get(rank, {}))
            out_path = os.path.join(workdir, f"out.{rank}.pkl")
            code = (
                "import pickle, sys\n"
                f"sys.path[:0] = {parent_path!r}\n"
                f"fn, args, kwargs = pickle.load(open({payload!r}, 'rb'))\n"
                "res = fn(*args, **kwargs)\n"
                f"pickle.dump(res, open({out_path!r}, 'wb'))\n"
            )
            procs.append((rank, out_path, subprocess.Popen(
                [sys.executable, "-c", code], env=env)))
        results = []
        failed = []
        for rank, out_path, p in procs:
            rc = p.wait()
            if rc != 0:
                failed.append((rank, rc))
            else:
                with open(out_path, "rb") as f:
                    results.append(pickle.load(f))
        if failed:
            raise RuntimeError(f"workers failed: {failed}")
        return results

    def shutdown(self):
        self._envs.clear()


class RayEngine:
    """Real Ray actors (reference NodeColocator/BaseHorovodWorker). Import
    of ray is deferred so the module stays importable without it."""

    def __init__(self, cpus_per_worker: int = 1, use_gpu: bool = False):
        try:
            import ray  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "horovod_tpu.ray's RayEngine requires the `ray` package; "
                "pass engine='local' for the subprocess engine") from e
        self._ray = __import__("ray")
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self._workers = []

    def start(self, num_workers: int, envs: dict[int, dict[str, str]]):
        ray = self._ray

        @ray.remote
        class _Worker:
            def __init__(self, env):
                os.environ.update(env)

            def hostname(self):
                import socket

                return socket.gethostname()

            def execute(self, blob):
                fn, args, kwargs = pickle.loads(blob)
                return fn(*args, **kwargs)

        opts = {"num_cpus": self.cpus_per_worker}
        if self.use_gpu:
            opts["num_gpus"] = 1
        self._workers = [
            _Worker.options(**opts).remote(envs.get(i, {}))
            for i in range(num_workers)
        ]

    def hostnames(self, num_workers: int) -> list[str]:
        ray = self._ray
        if not self._workers:
            # pre-start placement probe: schedule tiny tasks
            return [ray.get(ray.remote(lambda: __import__("socket")
                                       .gethostname()).remote())
                    for _ in range(num_workers)]
        return ray.get([w.hostname.remote() for w in self._workers])

    def free_port_on(self, hostname: str) -> int:
        """Probe a free port ON the named host (round-2 advisor finding: a
        driver-side probe says nothing about rank-0's host on a multi-node
        cluster). Soft node affinity; falls back to a driver probe when the
        host cannot be resolved to a Ray node."""
        ray = self._ray
        from ..runner.launch import _free_port

        try:
            node_id = next(
                n["NodeID"] for n in ray.nodes()
                if n.get("Alive") and (
                    n.get("NodeManagerHostname") == hostname
                    or n.get("NodeManagerAddress") == hostname))
            from ray.util.scheduling_strategies import (
                NodeAffinitySchedulingStrategy,
            )

            task = ray.remote(num_cpus=0)(_free_port).options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=node_id, soft=True))
            return ray.get(task.remote())
        except Exception:
            return _free_port()

    def run(self, fn, args, kwargs) -> list:
        ray = self._ray
        blob = _serializer().dumps((fn, args, kwargs))
        return ray.get([w.execute.remote(blob) for w in self._workers])

    def shutdown(self):
        self._workers = []


class RayExecutor:
    """Reference ray/runner.py:248 RayExecutor surface: start() places
    workers + establishes rendezvous; run()/execute() dispatch; shutdown().
    """

    def __init__(self, settings=None, num_workers: int = 1,
                 num_hosts: Optional[int] = None, num_slots: Optional[int] = None,
                 cpus_per_worker: int = 1, use_gpu: bool = False,
                 engine: str = "auto"):
        if num_hosts is not None and num_slots is not None:
            num_workers = num_hosts * num_slots
        self.num_workers = num_workers
        self.settings = settings
        if engine == "local":
            self._engine = LocalProcessEngine()
        elif engine == "ray":
            self._engine = RayEngine(cpus_per_worker, use_gpu)
        else:  # auto
            try:
                self._engine = RayEngine(cpus_per_worker, use_gpu)
            except ImportError:
                self._engine = LocalProcessEngine()
        self._rendezvous: Optional[RendezvousServer] = None
        self.coordinator = Coordinator()
        self._started = False

    def start(self, executable_cls: Any = None, executable_args=None):
        hostnames = self._engine.hostnames(self.num_workers)
        for rank, host in enumerate(hostnames):
            self.coordinator.register(host, rank)
        envs = self.coordinator.rank_envs()
        from ..runner.secret import get_or_mint_env_secret

        job_secret = get_or_mint_env_secret()  # before the server binds its key
        self._rendezvous = RendezvousServer()
        port = self._rendezvous.start()
        import socket

        addr = socket.gethostbyname(socket.gethostname())
        # one jax.distributed coordinator for the whole job, so workers'
        # hvd.init() bootstraps a real multi-process world (same env the
        # SSH launcher injects — runner/launch.py slot_env). Process 0 is
        # the one that BINDS the coordinator socket, so the address must
        # be rank 0's host — not necessarily the driver (RayEngine can
        # place worker 0 on another node), and the free-port probe runs
        # on that host through the engine.
        coord = f"{hostnames[0]}:{self._engine.free_port_on(hostnames[0])}"
        for rank, e in envs.items():
            e[env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR] = addr
            e[env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT] = str(port)
            e[env_schema.HOROVOD_SECRET_KEY] = job_secret
            e[env_schema.HOROVOD_CONTROLLER] = "kv"
            e[env_schema.HOROVOD_TPU_COORDINATOR] = coord
            e[env_schema.HOROVOD_TPU_NUM_PROCESSES] = str(self.num_workers)
            e[env_schema.HOROVOD_TPU_PROCESS_ID] = str(rank)
        self._engine.start(self.num_workers, envs)
        self._started = True

    def run(self, fn: Callable, args: tuple = (), kwargs: dict = None) -> list:
        """Run ``fn`` on every worker; returns rank-ordered results
        (reference run/execute)."""
        if not self._started:
            raise RuntimeError("call start() before run()")
        return self._engine.run(fn, args, kwargs or {})

    # reference aliases
    execute = run

    def run_remote(self, fn, args=(), kwargs=None):
        return self.run(fn, args, kwargs)

    def shutdown(self):
        self._engine.shutdown()
        if self._rendezvous is not None:
            self._rendezvous.stop()
            self._rendezvous = None
        self._started = False
