"""Elastic execution with Ray-backed host discovery.

Reference: /root/reference/horovod/ray/elastic.py — `RayHostDiscovery`
(:38, reads ``ray.nodes()`` and converts CPU/GPU resources to slots) and
`ElasticRayExecutor` (:149, wires that discovery into the elastic driver
and runs a user function across rendezvous rounds).

The round/launch/collect machinery is the shared
`horovod_tpu.elastic.executor.ElasticFunctionExecutor`; this module adds
the Ray discovery source. Worker placement: every worker runs as a
subprocess on the driver host (one process per slot — also the correct
shape for a single TPU host driving its local chips). Ray's role here is
*discovery*; dispatching workers as remote Ray actors (the reference's
BaseHorovodWorker placement) is not implemented — on a multi-node Ray
cluster the slots still execute locally.
"""

from __future__ import annotations

from typing import Optional

from ..elastic.discovery import FixedHosts, HostDiscovery
from ..elastic.executor import ElasticFunctionExecutor


class RayHostDiscovery(HostDiscovery):
    """Slots from Ray's global state (reference ray/elastic.py:38).

    Each alive node contributes ``CPU // cpus_per_slot`` slots (and, with
    ``use_gpu``, at most ``GPU // gpus_per_slot``).
    """

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        import ray

        mapping: dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("alive"):
                continue
            res = node.get("Resources", {})
            slots = int(res.get("CPU", 0)) // self.cpus_per_slot
            if self.use_gpu:
                slots = min(slots,
                            int(res.get("GPU", 0)) // self.gpus_per_slot)
            if slots:
                mapping[node["NodeManagerAddress"]] = slots
        return mapping


class ElasticRayExecutor(ElasticFunctionExecutor):
    """Reference ray/elastic.py:149 surface: ``create_settings`` →
    ``start()`` → ``run(fn)`` → rank-ordered results of the final
    successful round."""

    def __init__(self, settings=None, use_gpu: bool = False,
                 cpus_per_slot: int = 1, gpus_per_slot: int = 1,
                 env_vars: Optional[dict] = None,
                 override_discovery: bool = True,
                 discovery: Optional[HostDiscovery] = None):
        settings = settings or self.create_settings()
        if discovery is None:
            if override_discovery and self._ray_is_initialized():
                discovery = RayHostDiscovery(use_gpu, cpus_per_slot,
                                             gpus_per_slot)
            else:
                # hermetic fallback: all requested slots on this host
                discovery = FixedHosts({"localhost": (
                    settings.max_np or settings.min_np)})
        super().__init__(settings, discovery, env_vars)

    @staticmethod
    def _ray_is_initialized() -> bool:
        try:
            import ray

            return ray.is_initialized()
        except ImportError:
            return False
