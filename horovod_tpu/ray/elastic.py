"""Elastic execution over Ray (or hermetic local processes).

Reference: /root/reference/horovod/ray/elastic.py — `RayHostDiscovery`
(:38, reads ``ray.nodes()`` and converts CPU/GPU resources to slots) and
`ElasticRayExecutor` (:149, wires that discovery into the elastic driver
and runs a user function across rendezvous rounds).

TPU-native design: we reuse the restart-based `ElasticDriver`
(``horovod_tpu.elastic.driver``) rather than re-rendezvousing inside
worker processes — a JAX world is size-specialized, so each round
launches fresh worker processes that restore committed `State`.

Worker placement: every worker runs as a subprocess on the driver host
(the hermetic engine — one process per slot, which is also the correct
shape for a single TPU host driving its local chips). Ray's role here is
*discovery*: `RayHostDiscovery` turns the cluster's node table into the
elastic slot map. Dispatching workers as remote Ray actors (the
reference's BaseHorovodWorker placement) is not implemented — on a
multi-node Ray cluster the slots still execute locally.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from types import SimpleNamespace
from typing import Callable, Optional

from ..elastic.discovery import FixedHosts, HostDiscovery
from ..elastic.driver import ElasticDriver, WorkerHandle, make_base_env_fn
from ..runner.hosts import SlotInfo
from .runner import _serializer


class RayHostDiscovery(HostDiscovery):
    """Slots from Ray's global state (reference ray/elastic.py:38).

    Each alive node contributes ``CPU // cpus_per_slot`` slots (and, with
    ``use_gpu``, at most ``GPU // gpus_per_slot``).
    """

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        import ray

        mapping: dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("alive"):
                continue
            res = node.get("Resources", {})
            slots = int(res.get("CPU", 0)) // self.cpus_per_slot
            if self.use_gpu:
                slots = min(slots,
                            int(res.get("GPU", 0)) // self.gpus_per_slot)
            if slots:
                mapping[node["NodeManagerAddress"]] = slots
        return mapping


class _SubprocessFnWorker(WorkerHandle):
    """Runs the pickled user function in a subprocess on this host."""

    def __init__(self, payload: str, out_path: str, env: dict):
        code = (
            "import pickle, sys\n"
            f"sys.path[:0] = {list(sys.path)!r}\n"
            f"fn, args, kwargs = pickle.load(open({payload!r}, 'rb'))\n"
            "res = fn(*args, **kwargs)\n"
            f"pickle.dump(res, open({out_path!r}, 'wb'))\n"
        )
        self._p = subprocess.Popen([sys.executable, "-c", code], env=env)

    def poll(self):
        return self._p.poll()

    def terminate(self):
        try:
            self._p.terminate()
        except ProcessLookupError:
            pass


class ElasticRayExecutor:
    """Reference ray/elastic.py:149 surface: ``create_settings`` →
    ``start()`` → ``run(fn)`` → rank-ordered results of the final
    successful round."""

    @staticmethod
    def create_settings(min_np: int = 1, max_np: Optional[int] = None,
                        reset_limit: Optional[int] = None, **kwargs):
        return SimpleNamespace(min_np=min_np, max_np=max_np,
                               reset_limit=reset_limit, **kwargs)

    def __init__(self, settings=None, use_gpu: bool = False,
                 cpus_per_slot: int = 1, gpus_per_slot: int = 1,
                 env_vars: Optional[dict] = None,
                 override_discovery: bool = True,
                 discovery: Optional[HostDiscovery] = None):
        self.settings = settings or self.create_settings()
        self.env_vars = dict(env_vars or {})
        if discovery is not None:
            self.discovery = discovery
        elif override_discovery and self._ray_is_initialized():
            self.discovery = RayHostDiscovery(use_gpu, cpus_per_slot,
                                              gpus_per_slot)
        else:
            # hermetic fallback: all requested slots on this host
            self.discovery = FixedHosts({"localhost": (
                self.settings.max_np or self.settings.min_np)})
        self.driver: Optional[ElasticDriver] = None

    @staticmethod
    def _ray_is_initialized() -> bool:
        try:
            import ray

            return ray.is_initialized()
        except ImportError:
            return False

    def start(self):
        self.driver = ElasticDriver(
            self.discovery, min_np=self.settings.min_np,
            max_np=self.settings.max_np,
            reset_limit=getattr(self.settings, "reset_limit", None))

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> list:
        """Run ``fn`` elastically; returns the final round's rank-ordered
        results (reference ElasticRayExecutor.run)."""
        if self.driver is None:
            raise RuntimeError("call start() before run()")
        driver = self.driver
        workdir = tempfile.mkdtemp(prefix="hvd_ray_elastic_")
        payload = os.path.join(workdir, "fn.pkl")
        with open(payload, "wb") as f:
            _serializer().dump((fn, args, kwargs or {}), f)

        extra = dict(self.env_vars)
        extra.setdefault(
            "HOROVOD_ELASTIC_STORE",
            os.path.join(workdir, "state.pkl"))
        round_ranks: dict[int, list[int]] = {}

        # workers all run on this machine (see module docstring), so a
        # discovery hostname like a remote node IP must not leak into the
        # worker's identity
        base_env = make_base_env_fn(driver, extra,
                                    hostname_override="localhost")

        def create_worker(slot: SlotInfo, env: dict) -> WorkerHandle:
            ep = driver._epoch
            round_ranks.setdefault(ep, []).append(slot.rank)
            out = os.path.join(workdir, f"out.{ep}.{slot.rank}.pkl")
            return _SubprocessFnWorker(payload, out, env)

        rc = driver.run(create_worker, base_env)
        if rc != 0:
            raise RuntimeError(f"elastic run failed with exit code {rc}")
        final_ep = max(round_ranks)
        results = []
        for rank in sorted(round_ranks[final_ep]):
            out = os.path.join(workdir, f"out.{final_ep}.{rank}.pkl")
            with open(out, "rb") as f:
                results.append(pickle.load(f))
        return results

    def shutdown(self):
        if self.driver is not None:
            self.driver.stop()
            self.driver = None
