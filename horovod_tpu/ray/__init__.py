"""horovod_tpu.ray — Ray cluster integration (reference horovod/ray/).

`RayExecutor` places one worker per slot across the cluster, computes the
rank/topology env for each (reference runner.py:176 Coordinator +
NodeColocator :100), starts the rendezvous KV server, and runs user
functions on all workers.

TPU-shaped differences: workers bootstrap through
``jax.distributed.initialize`` + the HTTP rendezvous store (no Gloo, no
NIC negotiation), and the executor is built over a small engine
abstraction — `RayEngine` drives real Ray actors when ray is installed;
`LocalProcessEngine` drives local subprocesses so placement/topology logic
stays hermetically testable without a Ray cluster (the reference tests
against ``ray.init(local)``; this image has no ray wheel at all).
"""

from .elastic import (  # noqa: F401
    ElasticRayExecutor,
    RayHostDiscovery,
)
from .runner import (  # noqa: F401
    Coordinator,
    LocalProcessEngine,
    RayExecutor,
)
