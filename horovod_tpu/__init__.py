"""horovod_tpu — a TPU-native distributed deep-learning training framework
with the capabilities of Horovod (reference at /root/reference).

    import horovod_tpu as hvd

    hvd.init()
    # compiled path (hot): inside shard_map/jit, per-chip semantics
    grads = jax.tree.map(lambda g: hvd.allreduce(g, axis_name="hvd"), grads)
    # eager path: per-process semantics, named + async if desired
    h = hvd.allreduce_async(np.ones(4), name="t0")
    out = hvd.synchronize(h)

Design (see SURVEY.md): the data plane is XLA collectives over a
`jax.sharding.Mesh` riding ICI/DCN — not a port of the reference's
NCCL/MPI rings. The reference's background thread, negotiation protocol,
fusion buffers and response cache survive only in the slim eager/async
runtime (`horovod_tpu.ops.queue`); the compiled path needs none of them.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .common.context import (  # noqa: F401
    DEFAULT_AXIS,
    ProcessSet,
    add_process_set,
    ccl_built,
    context,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    global_process_set,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    num_shards,
    rank,
    remove_process_set,
    rocm_built,
    shard_id,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
    tpu_built,
    tpu_enabled,
)
from .common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from .ops.collectives import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allgather_object,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    broadcast_object,
    grouped_allreduce,
    join,
    reducescatter,
)
from .ops.adasum import (  # noqa: F401
    adasum_allreduce,
    adasum_allreduce_hierarchical,
)
from .ops.compression import Compression  # noqa: F401
from .ops.queue import TensorEntry

__version__ = "0.1.0"


def metrics_snapshot() -> dict:
    """Structured snapshot of the process-global metrics registry
    (counters / gauges / histograms as JSON-able dicts) — the Python-side
    view of what ``GET /metrics`` on the rendezvous server exposes. Valid
    before init and after shutdown; the registry is process-lifetime."""
    from .utils import metrics as _metrics

    return _metrics.get_registry().snapshot()


def trace_report() -> dict:
    """Summary of this rank's collective-lifecycle spans (utils/tracing.py):
    per-phase p50/p95 latencies (queue/negotiate/fuse/dispatch/total),
    span and error counts, open spans, and straggler attribution when the
    coordinator computed any. ``{"enabled": False}`` unless HOROVOD_TRACE
    was set at init. The merged cross-rank view is ``GET /timeline`` on
    the launcher's rendezvous server (docs/timeline.md)."""
    from .utils import tracing as _tracing

    return _tracing.report()


def perf_report() -> dict:
    """This rank's per-step performance ledger (utils/perfledger.py):
    derived goodput stats (negotiate p50/p95, exposed-comm fraction,
    wire bytes per step, plan hit rate, effective allreduce GB/s), the
    five-phase step decomposition, and — when ``HOROVOD_SLO_SPEC`` armed
    the budget engine — each budget's bound and breach state.
    ``{"enabled": False}`` unless HOROVOD_PERFLEDGER was set at init.
    The merged cross-rank view is ``GET /perf`` on the launcher's
    rendezvous server (docs/observability.md)."""
    from .utils import perfledger as _perfledger

    return _perfledger.report()


def memory_report() -> dict:
    """This rank's device-memory & compile ledger (utils/memledger.py):
    live/peak device bytes, per-component attribution (plan_cache /
    staging_ring / ef_residuals / sharded_state), the dominant suspect
    component, recent samples, and compile accounting (per-kind compile
    seconds, serialized program bytes, persistent-cache hit/miss).
    ``{"enabled": False}`` unless HOROVOD_MEMLEDGER was set at init.
    The merged cross-rank view is ``GET /memory`` on the launcher's
    rendezvous server (docs/observability.md)."""
    from .utils import memledger as _memledger

    return _memledger.report()


def anatomy_report() -> dict:
    """This rank's step-anatomy profile (utils/anatomy.py): the
    per-entity aggregate table (named chunks, negotiation rounds, host
    gaps, compile events — each with span and exposed-comm seconds), the
    critical-path summary (which entity bounds the most steps), and the
    Amdahl-style headroom estimates — ``overlap_headroom_s`` (step
    seconds recoverable by fully overlapping dispatched collectives) and
    ``replay_headroom_s`` (step seconds recoverable by eliminating
    negotiation + host gap via plan replay). ``{"enabled": False}``
    unless HOROVOD_ANATOMY was set at init. The merged cross-rank view
    is ``GET /anatomy`` on the launcher's rendezvous server
    (docs/observability.md, "Step anatomy & headroom")."""
    from .utils import anatomy as _anatomy

    return _anatomy.report()


def megaplan_report() -> dict:
    """This rank's whole-step replay status (ops/megaplan.py): capture
    and replay counters, the replay hit rate over post-capture cycles,
    per-reason invalidation counts, the stability threshold, and the
    live plan's shape (tensors/chunks/bytes) while one is captured.
    ``{"enabled": False}`` unless HOROVOD_MEGAPLAN was set at init
    (docs/performance.md, "Whole-step replay")."""
    from .ops import megaplan as _megaplan

    return _megaplan.report()


def checkpoint_report() -> dict:
    """This rank's async-checkpoint status (utils/async_ckpt.py): the
    checkpoint directory, newest durably committed step, last
    snapshot-copy stall and background-write durations, committed shard
    bytes, and whether a snapshot is queued or in flight.
    ``{"enabled": False}`` unless HOROVOD_ASYNC_CKPT was set at init.
    The merged cross-rank view is ``GET /checkpoint`` on the launcher's
    rendezvous server (docs/fault_tolerance.md, "Surviving
    preemption")."""
    from .utils import async_ckpt as _async_ckpt

    return _async_ckpt.report()


def health_report() -> dict:
    """This rank's fleet-health status (utils/health.py): the local
    verdict (healthy/degraded/critical), active anomalies, total
    anomalies latched, learned per-series baselines, the newest value
    of each history series, and the suspect rank when anomalies are
    active and straggler attribution is fresh. ``{"enabled": False}``
    unless HOROVOD_HEALTH was set at init. The merged cross-rank views
    are ``GET /history`` and ``GET /health`` on the launcher's
    rendezvous server (docs/observability.md, "Fleet health &
    history")."""
    from .utils import health as _health

    return _health.report()


def diagnose() -> dict:
    """The local diagnostic bundle (utils/diag.py): all-thread stacks,
    lockcheck state, a metrics snapshot, open tracing spans, the flight
    recorder's last events, and live-state probes (background-cycle beat,
    coordinator gather state). This is what the wedge watchdog dumps on a
    hang and what ``GET /debug`` on the rendezvous server merges across
    ranks — callable any time, init or not, for on-demand inspection.
    See docs/observability.md, "Debugging a hung job"."""
    from .utils import diag as _diag

    return _diag.build_bundle("diagnose")


# ---------------------------------------------------------------------------
# Async handle-based API (reference torch/mpi_ops.py:843-879: *_async, poll,
# synchronize, wait_and_clear)
# ---------------------------------------------------------------------------

def _runtime():
    ctx = context()
    if ctx.runtime is None:
        raise ValueError("horovod_tpu runtime not running; call hvd.init()")
    return ctx.runtime


def _default_name(prefix: str, tensor) -> str:
    rt = _runtime()
    return f"{prefix}.noname.{rt.handles._next}"


def allreduce_async(tensor, average: Optional[bool] = None, name: Optional[str] = None,
                    *, op: Optional[ReduceOp] = None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0, process_set: Optional[ProcessSet] = None,
                    compression=None) -> int:
    from .ops.collectives import _resolve_op
    from .ops.compression import NoneCompressor

    rt = _runtime()
    quant = None
    if compression is not None:
        quant = getattr(compression, "quant_spec", None)
        if quant is None and compression is not NoneCompressor \
                and not isinstance(compression, NoneCompressor):
            # cast compressors wrap the result synchronously — the async
            # handle path cannot carry the decompress context; quant
            # markers are a wire format the runtime owns, so they can
            raise ValueError(
                "allreduce_async supports Compression.none/int8/int4; "
                "use hvd.allreduce(...) for fp16/bf16 cast compression")
    return rt.enqueue(TensorEntry(
        name=name or _default_name("allreduce", tensor), op="allreduce",
        tensor=np.asarray(tensor), reduce_op=_resolve_op(op, average),
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set, quant=quant))


def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    rt = _runtime()
    return rt.enqueue(TensorEntry(
        name=name or _default_name("allgather", tensor), op="allgather",
        tensor=np.asarray(tensor), process_set=process_set))


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    rt = _runtime()
    ps = process_set or global_process_set()
    if not 0 <= int(root_rank) < ps.size:
        # synchronous, like the reference's HorovodBasics rank check
        # (test_torch.py test_horovod_broadcast_rank_error)
        raise ValueError(
            f"root_rank {root_rank} out of range for process set of size "
            f"{ps.size}")
    return rt.enqueue(TensorEntry(
        name=name or _default_name("broadcast", tensor), op="broadcast",
        tensor=np.asarray(tensor), root_rank=root_rank, process_set=process_set))


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    rt = _runtime()
    return rt.enqueue(TensorEntry(
        name=name or _default_name("alltoall", tensor), op="alltoall",
        tensor=np.asarray(tensor), splits=splits, process_set=process_set))


def reducescatter_async(tensor, name: Optional[str] = None, *,
                        op: Optional[ReduceOp] = None,
                        process_set: Optional[ProcessSet] = None) -> int:
    rt = _runtime()
    arr = np.asarray(tensor)
    nproc = (process_set or global_process_set()).cross_size
    if arr.ndim == 0 or arr.shape[0] % max(nproc, 1):
        # synchronous, like the broadcast rank check: the local shape and
        # process count fully determine the error — no need to surface it
        # from the cycle thread as HorovodInternalError
        raise ValueError("first dim must be divisible by the number of "
                         f"processes ({arr.shape} over {nproc})")
    return rt.enqueue(TensorEntry(
        name=name or _default_name("reducescatter", tensor), op="reducescatter",
        tensor=arr, reduce_op=op or ReduceOp.SUM,
        process_set=process_set))


def grouped_allreduce_async(tensors, average: Optional[bool] = None,
                            name: Optional[str] = None, *,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: Optional[ProcessSet] = None,
                            compression=None) -> list[int]:
    """Enqueue a group in one shot; the cycle loop fuses them into a single
    flat collective (reference grouped allreduce + GroupTable)."""
    # unnamed groups get a unique per-call base (reference
    # "grouped_allreduce.noname.<n>"): two concurrently pending unnamed
    # groups must not collide on the in-flight name guard
    base = name or _default_name("grouped_allreduce", tensors)
    return [allreduce_async(t, average, f"{base}.{i}", op=op,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor,
                            process_set=process_set, compression=compression)
            for i, t in enumerate(tensors)]


def poll(handle: int) -> bool:
    return _runtime().handles.poll(handle)


def synchronize(handle: int):
    return _runtime().handles.wait(handle)


# alias matching torch naming
wait = synchronize


# ---------------------------------------------------------------------------
# Parameter broadcast helpers (reference tensorflow/functions.py:47
# broadcast_variables / torch broadcast_parameters)
# ---------------------------------------------------------------------------

def broadcast_parameters(params, root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None):
    """Broadcast a pytree of arrays from ``root_rank`` — call once after
    init so all workers start from identical weights."""
    import jax

    return jax.tree.map(
        lambda p: broadcast(p, root_rank, process_set=process_set), params)


# optimizer layer re-exports (JAX-first API)
from .opt import (  # noqa: E402,F401
    DistributedOptimizer,
    DistributedGradientTransformation,
    ShardedDistributedOptimizer,
    ShardedUpdateEngine,
    cross_replica_sharded_optimizer,
    distributed_grad,
    plan_shard_layout,
)
