"""Elastic (fault-tolerant, resizable) training.

Reference: /root/reference/horovod/common/elastic.py run_fn (:151-175) —
the retry loop around the user's training function:

    @hvd.elastic.run
    def train(state):
        ...

    state = hvd.elastic.JaxState(params=params, opt_state=opt_state, epoch=0)
    train(state)

Semantics preserved: `HorovodInternalError` → restore committed state,
re-initialize, retry; `HostsUpdatedInterrupt` → re-sync (no restore) and
retry. See `horovod_tpu.elastic.driver` for the TPU-native restart model.
"""

from __future__ import annotations

import functools

from ..common.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from .discovery import FixedHosts, HostDiscovery, HostDiscoveryScript, HostManager
from .driver import ElasticDriver
from .registration import WorkerStateRegistry
from .state import JaxState, ObjectState, State

__all__ = [
    "run", "State", "ObjectState", "JaxState", "ElasticDriver",
    "HostDiscovery", "HostDiscoveryScript", "FixedHosts", "HostManager",
    "WorkerStateRegistry", "HorovodInternalError", "HostsUpdatedInterrupt",
]


def _reinitialize():
    """Re-init the collective runtime after a failure (reference
    elastic.py:159 _reset: shutdown + init).

    Bumps the controller generation (HOROVOD_ELASTIC_GEN): the new
    lockstep gets a fresh KV namespace so it can never read the dead
    generation's negotiation rounds (see ops/controller.py protocol
    notes). Ranks that miss a reinit starve on their old scope, hit the
    response timeout, and reinit too — converging generations."""
    import os

    from ..common import context as ctx_mod
    from ..common import env as env_schema
    from ..ops.collectives import clear_eager_cache, invalidate_fused_plans

    os.environ[env_schema.HOROVOD_ELASTIC_GEN] = str(
        int(os.environ.get(env_schema.HOROVOD_ELASTIC_GEN, "0")) + 1)

    ctx_mod.shutdown(drain=False)
    # fused/sharded plans first, THROUGH the accounting path: the new
    # generation's world may differ, so a replay would be a stale
    # topology — the invalidation-reason counter and the flightrec
    # breadcrumb must record that this was a deliberate drop, not LRU
    # churn. clear_eager_cache() then wipes the plain programs silently.
    invalidate_fused_plans()
    clear_eager_cache()
    # sharded-update engines replan their layout (and re-materialize
    # their state shard via load_full_state) under the new generation
    from ..opt import sharded as sharded_mod

    sharded_mod.notify_reshard()
    ctx_mod.init()


def run(func):
    """Decorator wrapping an elastic train function (reference
    elastic.py:151-175)."""

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        reset_required = False
        skip_sync = False
        while True:
            if reset_required:
                _reinitialize()
                state.on_reset()
                reset_required = False
            try:
                if not skip_sync:  # reference elastic.py: `if not skip_sync`
                    state.sync()
                skip_sync = False
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                state.restore()
                reset_required = True
            except HostsUpdatedInterrupt as e:
                # graceful membership change: keep current state; a
                # skip_sync update doesn't need the rank-0 broadcast either
                reset_required = True
                skip_sync = e.skip_sync

    return wrapper
