"""Host discovery + blacklist for elastic training.

Reference: /root/reference/horovod/runner/elastic/discovery.py — a
`HostDiscovery` interface, the `HostDiscoveryScript` implementation (invoke
the user script, parse ``hostname:slots`` lines) and `HostManager` with
blacklisting (:124).
"""

from __future__ import annotations

import subprocess
import threading
from ..runner.hosts import HostInfo


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Run the --host-discovery-script; stdout lines are ``host`` or
    ``host:slots`` (reference discovery.py:56-78)."""

    def __init__(self, script: str, default_slots: int = 1):
        self.script = script
        self.default_slots = default_slots

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        out = subprocess.run([self.script], capture_output=True, text=True,
                             timeout=60, check=True).stdout
        hosts: dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                h, s = line.rsplit(":", 1)
                hosts[h] = int(s)
            else:
                hosts[line] = self.default_slots
        return hosts


class FixedHosts(HostDiscovery):
    def __init__(self, hosts: dict[str, int]):
        self._hosts = dict(hosts)

    def set(self, hosts: dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks current/blacklisted hosts (reference HostManager,
    discovery.py:96-150)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._blacklist: set[str] = set()
        self._current: dict[str, int] = {}

    @property
    def current_hosts(self) -> dict[str, int]:
        with self._lock:
            return {h: s for h, s in self._current.items()
                    if h not in self._blacklist}

    def blacklist(self, host: str):
        """Reference: failing hosts are excluded from future assignments
        (discovery.py:124)."""
        with self._lock:
            self._blacklist.add(host)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._blacklist

    def update_available_hosts(self) -> bool:
        """Poll discovery; True if usable membership changed
        (reference HostManager.update_available_hosts)."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            prev = {h: s for h, s in self._current.items()
                    if h not in self._blacklist}
            self._current = found
            now = {h: s for h, s in found.items() if h not in self._blacklist}
            return prev != now

    def available_slots(self) -> int:
        return sum(self.current_hosts.values())
