"""Worker state registry: per-round readiness/success/failure accounting.

Reference: /root/reference/horovod/runner/elastic/registration.py —
`WorkerStateRegistry` counts READY/SUCCESS/FAILURE per rendezvous round,
gates the next rendezvous on everyone reporting, and feeds the driver's
blacklist/restart decisions (:28-139).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, verbose: bool = False):
        self._lock = threading.Condition()
        self._rounds: dict[int, dict[str, str]] = {}
        self._round = 0

    @property
    def round(self) -> int:
        return self._round

    def reset(self, new_round: Optional[int] = None):
        with self._lock:
            self._round = self._round + 1 if new_round is None else new_round
            self._rounds.setdefault(self._round, {})
            self._lock.notify_all()

    def record(self, worker: str, state: str, round_: Optional[int] = None):
        with self._lock:
            r = self._round if round_ is None else round_
            self._rounds.setdefault(r, {})[worker] = state
            self._lock.notify_all()

    def count(self, state: str, round_: Optional[int] = None) -> int:
        with self._lock:
            r = self._round if round_ is None else round_
            return sum(1 for s in self._rounds.get(r, {}).values() if s == state)

    def workers_in(self, state: str, round_: Optional[int] = None) -> list[str]:
        with self._lock:
            r = self._round if round_ is None else round_
            return sorted(w for w, s in self._rounds.get(r, {}).items()
                          if s == state)

    def wait_for(self, state: str, n: int, timeout: float = 30.0) -> bool:
        """Block until >= n workers report ``state`` this round."""
        end = time.monotonic() + timeout
        with self._lock:
            while self.count_unlocked(state) < n:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._lock.wait(remaining)
            return True

    def count_unlocked(self, state: str) -> int:
        return sum(1 for s in self._rounds.get(self._round, {}).values()
                   if s == state)
