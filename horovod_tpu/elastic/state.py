"""Elastic worker state: commit / restore / sync.

Reference: /root/reference/horovod/common/elastic.py — `State` with
commit/save/restore/sync + reset callbacks, `ObjectState` (:116), and the
per-framework states (torch/elastic/state.py TorchState with
Model/Optimizer/Sampler handlers).

TPU-native notes: snapshots of JAX pytrees are host numpy copies (device
buffers are invalidated by a TPU re-initialization, so an HBM snapshot
would not survive the event we are protecting against). ``sync()``
broadcasts from rank 0 with the object/parameter collectives.
"""

from __future__ import annotations

import copy
import os
from typing import Callable, Optional

import jax
import numpy as np

from ..common import env as env_schema
from ..common.exceptions import HostsUpdatedInterrupt


class State:
    """Base elastic state (reference common/elastic.py:27-115)."""

    def __init__(self, **kwargs):
        self._reset_callbacks: list[Callable] = []
        self._hm_forced = False
        # per-State acknowledgment of the shared listener's notification
        # count: every State observes every membership change (the
        # reference's WorkerNotificationManager delivers to every
        # registered state's own queue — consume-once-per-state, not
        # consume-once-per-process)
        self._hm_ack = _host_update_listener().change_count

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._hm_forced = False
        self._hm_ack = _host_update_listener().change_count
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self):
        self._hm_forced = True

    def commit(self):
        """Snapshot + check for membership changes (reference :60-72:
        commit = save + check_host_updates)."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if membership changed
        (reference :73-96; consistency across ranks comes from every
        worker polling the same driver epoch)."""
        if (self._hm_forced
                or _host_update_listener().change_count > self._hm_ack):
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class _HostUpdateListener:
    """Watches the driver's discovery epoch in the rendezvous KV store.

    Push-shaped replacement for the reference's WorkerNotificationService
    (runner/elastic/worker.py): ONE daemon thread per process (shared by
    every State, like the reference's single notification service) polls
    ``elastic/epoch`` every ~1 s and increments ``change_count`` whenever
    the observed epoch moves. States remember the count they last
    acknowledged, so ``check_host_updates()`` at commit points is an
    integer compare — membership changes surface at the next commit
    within ~1 s of the bump, commits never block on HTTP, every State
    sees every change, and a reset acknowledges exactly the changes that
    reset absorbed (no clear/watcher race: the watcher owns all its
    state; the single watcher thread's GETs are sequential, so the
    observed epoch sequence is ordered).
    """

    WATCH_INTERVAL_S = 1.0

    def __init__(self, carry: Optional[tuple] = None):
        import threading

        self._seen_epoch = int(
            os.environ.get(env_schema.HOROVOD_ELASTIC_EPOCH, "0"))
        addr = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR)
        port = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT)
        self.env_key = (addr, port)
        self._client = None
        self.change_count = 0
        if carry is not None:
            # a rebuild must not invalidate States' acknowledged counts:
            # the counter is monotonic across listener generations
            self._seen_epoch, self.change_count = carry
        self._stop = threading.Event()
        if addr and port:
            from ..runner.http_server import KVStoreClient

            self._client = KVStoreClient(addr, int(port))
            threading.Thread(target=self._watch, daemon=True,
                             name="hvd-host-updates").start()

    def _watch(self):
        while not self._stop.is_set():
            cur = self._fetch_epoch()
            if cur is not None and cur != self._seen_epoch:
                from ..utils import flightrec

                flightrec.note("elastic_generation", epoch=cur,
                               previous=self._seen_epoch)
                # a resize drops plans/residuals and rebuilds sharded
                # layouts — stamp a memory sample at the boundary so
                # before/after attribution survives in the ring
                from ..utils import memledger

                memledger.sample_event("elastic_resize")
                self._seen_epoch = cur
                self.change_count += 1
            self._stop.wait(self.WATCH_INTERVAL_S)

    def _fetch_epoch(self) -> Optional[int]:
        if self._client is None:
            return None
        try:
            return int(self._client.get("elastic", "epoch", timeout=1.0))
        except Exception:
            return None

    def stop(self):
        self._stop.set()


_shared_listener: Optional[_HostUpdateListener] = None


def _host_update_listener() -> _HostUpdateListener:
    """Process-wide singleton: many State instances, one watcher thread.
    Rebuilt when the rendezvous env appears or points somewhere new, so
    States never keep watching a dead store; States re-resolve the
    singleton on every use rather than capturing a reference."""
    global _shared_listener
    env_key = (os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR),
               os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT))
    if _shared_listener is None or _shared_listener.env_key != env_key:
        carry = None
        if _shared_listener is not None:
            _shared_listener.stop()
            carry = (_shared_listener._seen_epoch,
                     _shared_listener.change_count)
        _shared_listener = _HostUpdateListener(carry)
    return _shared_listener


class ObjectState(State):
    """Elastic state of picklable attributes (reference ObjectState :116).

    ``checkpoint_format`` selects the on-disk store layout: "pickle"
    (single file, default) or "orbax" (tensorstore pytree directory —
    see utils/checkpoint.py)."""

    def __init__(self, store_path: Optional[str] = None,
                 checkpoint_format: str = "pickle", **kwargs):
        super().__init__()
        from ..utils import checkpoint as ckpt

        self._ckpt = ckpt
        self._ckpt_format = checkpoint_format
        self._store_path = store_path or os.environ.get(
            env_schema.HOROVOD_ELASTIC_STORE, "")
        self._saved: dict = {}
        self._attrs = list(kwargs.keys())
        for k, v in kwargs.items():
            setattr(self, k, v)
        # resume semantics: a pre-existing store (left by a previous worker
        # incarnation's commit) wins over the constructor defaults — this is
        # how state survives the TPU restart-based resize (driver.py
        # docstring); never clobber it with fresh defaults here.
        if self._store_path and ckpt.exists(self._store_path):
            self._saved = ckpt.load_pytree(self._store_path)
            self.restore()
        else:
            self.save()

    def _snapshot(self) -> dict:
        return {k: copy.deepcopy(getattr(self, k)) for k in self._attrs}

    def save(self):
        self._saved = self._snapshot()
        if self._store_path and self._is_store_writer():
            self._ckpt.save_pytree(self._store_path, self._saved,
                                   format=self._ckpt_format)

    @staticmethod
    def _is_store_writer() -> bool:
        """One writer per host: elastic slots on a host share one
        HOROVOD_ELASTIC_STORE path, and concurrent commits raced in the
        tmp/rotate dance (round-2 advisor finding). sync() broadcasts state
        from rank 0 before commits, so any single rank's snapshot is a
        valid resume point; the lowest local rank writes it."""
        try:
            from .. import local_rank

            return local_rank() == 0
        except Exception:
            return True  # uninitialized/single-process: no peers to race

    def restore(self):
        if not self._saved and self._store_path and \
                self._ckpt.exists(self._store_path):
            self._saved = self._ckpt.load_pytree(self._store_path)
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        from ..ops.collectives import broadcast_object

        for k in self._attrs:
            setattr(self, k, broadcast_object(getattr(self, k), root_rank=0))
        self.save()


class JaxState(ObjectState):
    """Elastic state for JAX training: pytrees snapshot to host numpy
    (the per-framework State of reference P3/P4, re-shaped for JAX).

    Example:
        state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                     epoch=0, batch=0)
    """

    def _snapshot(self) -> dict:
        out = {}
        for k in self._attrs:
            v = getattr(self, k)
            out[k] = jax.tree.map(
                lambda x: np.asarray(x) if hasattr(x, "dtype") else copy.deepcopy(x), v)
        return out

    def sync(self):
        from ..ops.collectives import broadcast_object
        from ..ops.queue import TensorEntry  # noqa: F401  (runtime must be up)

        for k in self._attrs:
            v = getattr(self, k)
            leaves, treedef = jax.tree.flatten(v)
            if leaves and all(hasattr(l, "dtype") for l in leaves):
                from .. import broadcast_parameters

                setattr(self, k, broadcast_parameters(v, root_rank=0))
            else:
                setattr(self, k, broadcast_object(v, root_rank=0))
        self.save()

    def restore_from_shards(self, engine, *, params_attr: str = "params",
                            opt_state_attr: str = "opt_state",
                            directory: Optional[str] = None) -> Optional[int]:
        """Restore the optimizer state from an async shard checkpoint
        (utils/async_ckpt.py) written by a previous incarnation —
        including the N→M resize case: saved shards are reassembled
        through the *saved* world's deterministic layout and re-sliced
        under ``engine``'s current one (the PR 7 ``full_state()``
        contract). Replicated leaves saved by rank 0 are applied to any
        matching state attributes (e.g. ``params``). Returns the
        restored step, or None when the directory holds no complete,
        checksum-clean snapshot (caller proceeds from the committed
        object store, or cold)."""
        from ..utils import async_ckpt

        directory = (directory
                     or env_schema.get_str(env_schema.HOROVOD_ASYNC_CKPT_DIR)
                     or async_ckpt.DEFAULT_DIR)
        params = getattr(self, params_attr)
        try:
            manifest, state, replicated = async_ckpt.restore_sharded(
                directory, params, engine)
        except async_ckpt.CheckpointError:
            return None
        setattr(self, opt_state_attr, state)
        if isinstance(replicated, dict):
            for k, v in replicated.items():
                if k in self._attrs:
                    setattr(self, k, v)
        self.save()
        return manifest["step"]
