"""Elastic worker state: commit / restore / sync.

Reference: /root/reference/horovod/common/elastic.py — `State` with
commit/save/restore/sync + reset callbacks, `ObjectState` (:116), and the
per-framework states (torch/elastic/state.py TorchState with
Model/Optimizer/Sampler handlers).

TPU-native notes: snapshots of JAX pytrees are host numpy copies (device
buffers are invalidated by a TPU re-initialization, so an HBM snapshot
would not survive the event we are protecting against). ``sync()``
broadcasts from rank 0 with the object/parameter collectives.
"""

from __future__ import annotations

import copy
import os
from typing import Callable, Optional

import jax
import numpy as np

from ..common.exceptions import HostsUpdatedInterrupt


class State:
    """Base elastic state (reference common/elastic.py:27-115)."""

    def __init__(self, **kwargs):
        self._reset_callbacks: list[Callable] = []
        self._host_messages = _host_update_listener()

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages.clear()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self):
        self._host_messages.bump()

    def commit(self):
        """Snapshot + check for membership changes (reference :60-72:
        commit = save + check_host_updates)."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if membership changed
        (reference :73-96; consistency across ranks comes from every
        worker polling the same driver epoch)."""
        if self._host_messages.changed():
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError


class _HostUpdateListener:
    """Watches the driver's discovery epoch in the rendezvous KV store.

    Push-shaped replacement for the reference's WorkerNotificationService
    (runner/elastic/worker.py): ONE daemon thread per process (shared by
    every State, like the reference's single notification service) polls
    ``elastic/epoch`` every ~1 s and latches a flag when the driver bumps
    it, so ``check_host_updates()`` at commit points is a flag read —
    membership changes surface at the next commit within ~1 s of the
    bump, however long the commit interval is, and commits never block
    on HTTP.
    """

    WATCH_INTERVAL_S = 1.0

    def __init__(self):
        import threading

        self._base_epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))
        addr = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR")
        port = os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT")
        self._client = None
        self._forced = False
        self._lock = threading.Lock()
        self._updated = threading.Event()
        self._stop = threading.Event()
        if addr and port:
            from ..runner.http_server import KVStoreClient

            self._client = KVStoreClient(addr, int(port))
            threading.Thread(target=self._watch, daemon=True,
                             name="hvd-host-updates").start()

    def _watch(self):
        while not self._stop.is_set():
            cur = self.current_epoch()  # HTTP outside the lock
            with self._lock:
                # compare under the lock against the *current* base: a
                # clear() that rebased while our GET was in flight must not
                # be overridden by the stale comparison (spurious restart)
                if cur != self._base_epoch:
                    self._updated.set()
            self._stop.wait(self.WATCH_INTERVAL_S)

    def bump(self):
        self._forced = True

    def clear(self):
        cur = self.current_epoch()
        with self._lock:
            self._forced = False
            self._base_epoch = cur
            self._updated.clear()

    def stop(self):
        self._stop.set()

    def current_epoch(self) -> int:
        if self._client is None:
            return self._base_epoch
        try:
            return int(self._client.get("elastic", "epoch", timeout=1.0))
        except Exception:
            return self._base_epoch

    def changed(self) -> bool:
        return self._forced or self._updated.is_set()


_shared_listener: Optional[_HostUpdateListener] = None


def _host_update_listener() -> _HostUpdateListener:
    """Process-wide singleton: many State instances, one watcher thread
    (and one rebuilt if the rendezvous env appears after the first use)."""
    global _shared_listener
    if (_shared_listener is None
            or (_shared_listener._client is None
                and os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR"))):
        if _shared_listener is not None:
            _shared_listener.stop()
        _shared_listener = _HostUpdateListener()
    return _shared_listener


class ObjectState(State):
    """Elastic state of picklable attributes (reference ObjectState :116).

    ``checkpoint_format`` selects the on-disk store layout: "pickle"
    (single file, default) or "orbax" (tensorstore pytree directory —
    see utils/checkpoint.py)."""

    def __init__(self, store_path: Optional[str] = None,
                 checkpoint_format: str = "pickle", **kwargs):
        super().__init__()
        from ..utils import checkpoint as ckpt

        self._ckpt = ckpt
        self._ckpt_format = checkpoint_format
        self._store_path = store_path or os.environ.get("HOROVOD_ELASTIC_STORE", "")
        self._saved: dict = {}
        self._attrs = list(kwargs.keys())
        for k, v in kwargs.items():
            setattr(self, k, v)
        # resume semantics: a pre-existing store (left by a previous worker
        # incarnation's commit) wins over the constructor defaults — this is
        # how state survives the TPU restart-based resize (driver.py
        # docstring); never clobber it with fresh defaults here.
        if self._store_path and ckpt.exists(self._store_path):
            self._saved = ckpt.load_pytree(self._store_path)
            self.restore()
        else:
            self.save()

    def _snapshot(self) -> dict:
        return {k: copy.deepcopy(getattr(self, k)) for k in self._attrs}

    def save(self):
        self._saved = self._snapshot()
        if self._store_path and self._is_store_writer():
            self._ckpt.save_pytree(self._store_path, self._saved,
                                   format=self._ckpt_format)

    @staticmethod
    def _is_store_writer() -> bool:
        """One writer per host: elastic slots on a host share one
        HOROVOD_ELASTIC_STORE path, and concurrent commits raced in the
        tmp/rotate dance (round-2 advisor finding). sync() broadcasts state
        from rank 0 before commits, so any single rank's snapshot is a
        valid resume point; the lowest local rank writes it."""
        try:
            from .. import local_rank

            return local_rank() == 0
        except Exception:
            return True  # uninitialized/single-process: no peers to race

    def restore(self):
        if not self._saved and self._store_path and \
                self._ckpt.exists(self._store_path):
            self._saved = self._ckpt.load_pytree(self._store_path)
        for k, v in self._saved.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        from ..ops.collectives import broadcast_object

        for k in self._attrs:
            setattr(self, k, broadcast_object(getattr(self, k), root_rank=0))
        self.save()


class JaxState(ObjectState):
    """Elastic state for JAX training: pytrees snapshot to host numpy
    (the per-framework State of reference P3/P4, re-shaped for JAX).

    Example:
        state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                     epoch=0, batch=0)
    """

    def _snapshot(self) -> dict:
        out = {}
        for k in self._attrs:
            v = getattr(self, k)
            out[k] = jax.tree.map(
                lambda x: np.asarray(x) if hasattr(x, "dtype") else copy.deepcopy(x), v)
        return out

    def sync(self):
        from ..ops.collectives import broadcast_object
        from ..ops.queue import TensorEntry  # noqa: F401  (runtime must be up)

        for k in self._attrs:
            v = getattr(self, k)
            leaves, treedef = jax.tree.flatten(v)
            if leaves and all(hasattr(l, "dtype") for l in leaves):
                from .. import broadcast_parameters

                setattr(self, k, broadcast_parameters(v, root_rank=0))
            else:
                setattr(self, k, broadcast_object(v, root_rank=0))
        self.save()
