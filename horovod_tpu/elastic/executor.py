"""Generic elastic function executor: run a pickled user function across
rendezvous rounds of local worker processes.

This is the engine under both cluster adapters —
`horovod_tpu.ray.ElasticRayExecutor` (discovery = Ray node table) and
`horovod_tpu.spark.run_elastic` (discovery = Spark executor hosts). The
restart-based recovery model is the elastic driver's (see
`elastic/driver.py` docstring): each round launches fresh worker
processes; committed `State` snapshots carry progress across rounds.

Reference analogue: the per-framework elastic runners
(/root/reference/horovod/ray/elastic.py:149,
/root/reference/horovod/spark/runner.py:306) both reduce to "drive the
elastic driver, run fn in each worker, return the last round's results".
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from types import SimpleNamespace
from typing import Callable, Optional

from .discovery import HostDiscovery
from .driver import ElasticDriver, WorkerHandle, make_base_env_fn
from ..runner.hosts import SlotInfo


def _serializer(require_by_value: bool = False):
    """cloudpickle when available (serializes __main__-defined and lambda
    functions by value); plain pickle otherwise. Pass
    ``require_by_value=True`` when the payload contains closures/lambdas
    (the estimators' worker functions) so the failure is a clear error
    rather than a pickling traceback."""
    try:
        import cloudpickle

        return cloudpickle
    except ImportError:
        if require_by_value:
            raise ImportError(
                "this code path serializes closures and requires the "
                "`cloudpickle` package")
        return pickle


class _SubprocessFnWorker(WorkerHandle):
    """Runs the pickled user function in a subprocess on this host."""

    def __init__(self, payload: str, out_path: str, env: dict):
        code = (
            "import pickle, sys\n"
            f"sys.path[:0] = {list(sys.path)!r}\n"
            f"fn, args, kwargs = pickle.load(open({payload!r}, 'rb'))\n"
            "res = fn(*args, **kwargs)\n"
            f"pickle.dump(res, open({out_path!r}, 'wb'))\n"
        )
        self._p = subprocess.Popen([sys.executable, "-c", code], env=env)

    def poll(self):
        return self._p.poll()

    def terminate(self):
        try:
            self._p.terminate()
        except ProcessLookupError:
            pass


class ElasticFunctionExecutor:
    """``create_settings`` → ``start()`` → ``run(fn)`` → rank-ordered
    results of the final successful round."""

    @staticmethod
    def create_settings(min_np: int = 1, max_np: Optional[int] = None,
                        reset_limit: Optional[int] = None, **kwargs):
        return SimpleNamespace(min_np=min_np, max_np=max_np,
                               reset_limit=reset_limit, **kwargs)

    def __init__(self, settings, discovery: HostDiscovery,
                 env_vars: Optional[dict] = None):
        self.settings = settings
        self.discovery = discovery
        self.env_vars = dict(env_vars or {})
        self.driver: Optional[ElasticDriver] = None

    def start(self):
        self.driver = ElasticDriver(
            self.discovery, min_np=self.settings.min_np,
            max_np=self.settings.max_np,
            reset_limit=getattr(self.settings, "reset_limit", None))

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> list:
        if self.driver is None:
            raise RuntimeError("call start() before run()")
        driver = self.driver
        workdir = tempfile.mkdtemp(prefix="hvd_elastic_fn_")
        payload = os.path.join(workdir, "fn.pkl")
        with open(payload, "wb") as f:
            _serializer().dump((fn, args, kwargs or {}), f)

        extra = dict(self.env_vars)
        extra.setdefault("HOROVOD_ELASTIC_STORE",
                         os.path.join(workdir, "state.pkl"))
        round_ranks: dict[int, list[int]] = {}

        # workers all run on this machine (one process per slot), so a
        # discovery hostname like a remote node IP must not leak into the
        # worker's identity
        base_env = make_base_env_fn(driver, extra,
                                    hostname_override="localhost")

        def create_worker(slot: SlotInfo, env: dict) -> WorkerHandle:
            ep = driver._epoch
            round_ranks.setdefault(ep, []).append(slot.rank)
            out = os.path.join(workdir, f"out.{ep}.{slot.rank}.pkl")
            return _SubprocessFnWorker(payload, out, env)

        rc = driver.run(create_worker, base_env)
        if rc != 0:
            raise RuntimeError(f"elastic run failed with exit code {rc}")
        final_ep = max(round_ranks)
        results = []
        for rank in sorted(round_ranks[final_ep]):
            out = os.path.join(workdir, f"out.{final_ep}.{rank}.pkl")
            with open(out, "rb") as f:
                results.append(pickle.load(f))
        return results

    def shutdown(self):
        if self.driver is not None:
            self.driver.stop()
            self.driver = None
