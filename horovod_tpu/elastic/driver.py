"""Elastic driver: dynamic membership, stable rank assignment, restart.

Reference: /root/reference/horovod/runner/elastic/driver.py —
`ElasticDriver` polls the discovery script every second (:181-201), computes
stable rank assignments keeping at least one surviving host (:233-248),
spawns/kills worker slots, blacklists failing hosts, and coordinates
rendezvous rounds with `WorkerStateRegistry`.

TPU-native recovery model (deliberate divergence, documented): the
reference re-rendezvouses *inside* surviving worker processes
(gloo_context.cc:154-192 elastic scope). A JAX process cannot cheaply
re-size its world in-process (the distributed runtime and all compiled
programs are world-size-specialized), so on membership change the driver
bumps the epoch, terminates workers, and relaunches them with fresh
HOROVOD_* env; workers resume from their last committed `State` snapshot
(`JaxState` filesystem store + rank-0 sync broadcast). Recompilation on
resize is unavoidable on TPU either way — XLA programs embed the mesh.
Within a process lifetime, `HorovodInternalError` recovery (collective
failure) restores the in-memory snapshot without restart, same as the
reference.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
import time
from typing import Callable, Optional

from ..common import env as env_schema
from ..runner.hosts import HostInfo, SlotInfo, get_host_assignments
from ..runner.http_server import RendezvousServer
from ..utils import faults as faults_mod
from ..utils import metrics as metrics_mod
from ..utils import retry as retry_mod
from .discovery import HostDiscoveryScript, HostManager
from .registration import FAILURE, SUCCESS, WorkerStateRegistry

LOG = logging.getLogger("horovod_tpu")

DISCOVER_INTERVAL_S = 1.0


class WorkerHandle:
    """Minimal process handle protocol (test doubles use threads)."""

    def poll(self) -> Optional[int]:
        raise NotImplementedError

    def terminate(self):
        raise NotImplementedError

    def kill(self):
        """Hard stop (SIGKILL escalation); defaults to terminate() for
        handles with no harder signal (thread-backed test doubles)."""
        self.terminate()


class _SubprocessWorker(WorkerHandle):
    def __init__(self, popen: subprocess.Popen, stream_threads=()):
        self.popen = popen
        self._streams = list(stream_threads)

    def poll(self):
        rc = self.popen.poll()
        if rc is not None and self._streams:
            # drain the output streams before the driver acts on the
            # exit: the tee files must hold the rank's full output, and
            # a respawned incarnation must not interleave with this one
            for t in self._streams:
                t.join(timeout=10)
                if t.is_alive():
                    # a forked child still holds the stdout pipe open: the
                    # stream never EOFs, and a respawned incarnation may
                    # interleave with it in the tee file
                    LOG.warning(
                        "worker output stream still open 10 s after exit "
                        "(orphaned child holding the pipe?); tee file may "
                        "interleave with the next incarnation")
            self._streams = []
        return rc

    def terminate(self):
        try:
            self.popen.send_signal(signal.SIGTERM)
        except ProcessLookupError:
            pass

    def kill(self):
        try:
            self.popen.kill()
        except ProcessLookupError:
            pass


class ElasticDriver:
    """Round-based elastic driver with respawn-before-blacklist.

    A worker failure used to blacklist its host on the first strike —
    one transient SSH drop or TPU-VM preemption blip permanently shrank
    the job. Failures are now a per-host strike count: below
    ``respawn_retries`` (``HOROVOD_ELASTIC_RESPAWN_ATTEMPTS``, default 1)
    the host is *retried* in the next round after a full-jitter backoff
    (``HOROVOD_ELASTIC_RESPAWN_BACKOFF`` scales it); only exhausting the
    budget blacklists. A worker exiting 0 clears its host's strikes, so
    the budget is per failure burst, not per job lifetime.
    """

    def __init__(self, discovery, min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 respawn_retries: Optional[int] = None,
                 respawn_backoff_s: Optional[float] = None):
        self.host_manager = HostManager(discovery)
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.respawn_retries = (
            respawn_retries if respawn_retries is not None
            else env_schema.get_int(
                env_schema.HOROVOD_ELASTIC_RESPAWN_ATTEMPTS, 1))
        self.respawn_backoff_s = (
            respawn_backoff_s if respawn_backoff_s is not None
            else env_schema.get_float(
                env_schema.HOROVOD_ELASTIC_RESPAWN_BACKOFF, 1.0))
        self.registry = WorkerStateRegistry()
        self.rendezvous = RendezvousServer()
        self._prev_host_order: list[str] = []
        self._prev_slot_ranks: set[int] = set()
        self._host_strikes: dict[str, int] = {}
        self._epoch = 0
        self._resets = 0
        self._stop = threading.Event()
        reg = metrics_mod.get_registry()
        self._m_rank_added = reg.counter(
            "hvd_elastic_ranks_added_total",
            "worker ranks added across elastic rounds")
        self._m_rank_removed = reg.counter(
            "hvd_elastic_ranks_removed_total",
            "worker ranks removed across elastic rounds")
        self._m_resets = reg.counter(
            "hvd_elastic_resets_total",
            "elastic resets (membership change or worker failure)")
        self._m_failures = reg.counter(
            "hvd_elastic_worker_failures_total",
            "worker processes that exited nonzero")
        self._m_respawns = reg.counter(
            "hvd_elastic_respawns_total",
            "failed hosts retried (respawn-before-blacklist)")
        self._m_blacklists = reg.counter(
            "hvd_elastic_blacklists_total",
            "hosts blacklisted after exhausting their respawn budget")
        self._m_epoch = reg.gauge("hvd_elastic_epoch",
                                  "current elastic incarnation")
        self._m_world = reg.gauge("hvd_elastic_world_size",
                                  "slots assigned in the current round")

    # -- assignments ---------------------------------------------------------
    def compute_assignments(self) -> list[SlotInfo]:
        """Stable assignment (reference _update_host_assignments,
        driver.py:233): surviving hosts keep their previous order (so rank 0
        stays on a surviving host and in-memory state is recoverable from
        it); new hosts append in sorted order."""
        hosts = self.host_manager.current_hosts
        if self._prev_host_order and not any(h in hosts for h in self._prev_host_order):
            raise RuntimeError(
                "no hosts from the previous round survive; cannot recover "
                "state (reference driver.py:242-248)")
        order = [h for h in self._prev_host_order if h in hosts]
        order += sorted(h for h in hosts if h not in order)
        np_avail = sum(hosts[h] for h in order)
        np = min(np_avail, self.max_np) if self.max_np else np_avail
        if np < self.min_np:
            raise RuntimeError(
                f"available slots {np_avail} < min_np {self.min_np}")
        slots = get_host_assignments([HostInfo(h, hosts[h]) for h in order], np)
        self._prev_host_order = order
        ranks = {s.rank for s in slots}
        self._m_rank_added.inc(len(ranks - self._prev_slot_ranks))
        self._m_rank_removed.inc(len(self._prev_slot_ranks - ranks))
        self._prev_slot_ranks = ranks
        self._m_world.set(len(slots))
        return slots

    # -- epoch / notification ------------------------------------------------
    def publish_epoch(self):
        from ..runner.http_server import KVStoreClient

        client = KVStoreClient("127.0.0.1", self.rendezvous.port)
        client.put("elastic", "epoch", str(self._epoch).encode())

    def bump_epoch(self):
        self._epoch += 1
        self._m_epoch.set(self._epoch)
        self.publish_epoch()

    # -- main loop -----------------------------------------------------------
    def run(self, create_worker: Callable[[SlotInfo, dict], WorkerHandle],
            base_env_fn: Callable[[SlotInfo], dict]) -> int:
        """Rounds of launch→monitor until global success or unrecoverable
        failure. Returns a process exit code."""
        self.rendezvous.start()
        self.host_manager.update_available_hosts()
        self.publish_epoch()
        while not self._stop.is_set():
            try:
                slots = self.compute_assignments()
            except RuntimeError as e:
                LOG.error("elastic: %s", e)
                return 1
            # the round's full assignment, visible to per-slot env
            # factories that need cross-slot facts (who is rank 0, which
            # hosts are remote) — see make_base_env_fn
            self.current_slots = slots
            self.registry.reset()
            workers: dict[int, tuple[SlotInfo, WorkerHandle]] = {}
            spawn_failed = None  # (slot, exception)
            for slot in slots:
                env = base_env_fn(slot)
                env["HOROVOD_ELASTIC_EPOCH"] = str(self._epoch)
                env["HOROVOD_ELASTIC"] = "1"
                try:
                    faults_mod.fault_point("elastic.spawn")
                    workers[slot.rank] = (slot, create_worker(slot, env))
                except Exception as e:
                    # SSH refused / binary missing / preempted mid-spawn:
                    # same lifecycle as a worker failure on that host
                    spawn_failed = (slot, e)
                    break
            if spawn_failed is not None:
                slot, e = spawn_failed
                self._terminate(workers)
                rc = self._host_failure(slot, f"spawn failed: {e!r}")
            else:
                rc = self._monitor_round(workers)
            if rc is not None:
                return rc
            # membership changed or failure: next round
            if self.reset_limit is not None and self._resets >= self.reset_limit:
                LOG.error("elastic: reset limit %d reached", self.reset_limit)
                return 1
        return 0

    def _monitor_round(self, workers) -> Optional[int]:
        """None → start a new round; int → final exit code."""
        last_discovery = 0.0
        alive = dict(workers)
        failed: Optional[tuple[SlotInfo, int]] = None
        while alive:
            now = time.monotonic()
            if now - last_discovery >= DISCOVER_INTERVAL_S:
                last_discovery = now
                try:
                    faults_mod.fault_point("elastic.heartbeat")
                    changed = self.host_manager.update_available_hosts()
                except faults_mod.FaultInjectedError:
                    changed = False  # skipped heartbeat: detection delayed
                if changed:
                    LOG.info("elastic: host membership changed; resetting")
                    self._resets += 1
                    self._m_resets.inc()
                    self.bump_epoch()
                    self._terminate(alive)
                    return None
            for rank in list(alive):
                slot, h = alive[rank]
                rc = h.poll()
                if rc is None:
                    continue
                del alive[rank]
                if rc == 0:
                    self.registry.record(f"{slot.hostname}:{slot.local_rank}",
                                         SUCCESS)
                    # a clean exit proves the host healthy: the respawn
                    # budget is per failure burst, not per job lifetime
                    self._host_strikes.pop(slot.hostname, None)
                else:
                    self.registry.record(f"{slot.hostname}:{slot.local_rank}",
                                         FAILURE)
                    failed = (slot, rc)
                    break
            if failed:
                slot, rc = failed
                self._terminate(alive)
                return self._host_failure(slot, f"exited with code {rc}")
            time.sleep(0.05)
        return 0  # every worker exited 0

    def _host_failure(self, slot: SlotInfo, what: str) -> Optional[int]:
        """Strike the failed slot's host: respawn it (with backoff) while
        the per-host budget lasts, blacklist when it is exhausted. The
        log line carries rank, local slot, failure detail, and the
        blacklist decision so a post-mortem needs no KV-log archaeology.
        None → start a new round; int → final exit code."""
        host = slot.hostname
        self._m_failures.inc()
        strikes = self._host_strikes.get(host, 0) + 1
        self._host_strikes[host] = strikes
        budget = self.respawn_retries
        if strikes > budget:
            decision = (
                "blacklisting (first strike; respawn retries disabled)"
                if budget == 0 else
                f"blacklisting (respawn retries exhausted: "
                f"{strikes - 1}/{budget})")
            delay = 0.0
            self.host_manager.blacklist(host)
            self._m_blacklists.inc()
        else:
            # full-jitter exponential backoff between respawn rounds:
            # preempted-VM replacements and SSH daemons both need a
            # breath, and synchronized multi-host failures must not
            # hammer the discovery/spawn path in lockstep
            delay = retry_mod.RetryPolicy(
                base_delay_s=self.respawn_backoff_s,
                max_delay_s=max(self.respawn_backoff_s, 30.0),
            ).backoff_delay(strikes)
            decision = (f"respawning before blacklist "
                        f"(attempt {strikes}/{budget}, "
                        f"backoff {delay:.1f}s)")
            self._m_respawns.inc()
        LOG.warning(
            "elastic: worker rank %d (slot %s:%d) %s; %s",
            slot.rank, host, slot.local_rank, what, decision)
        self._resets += 1
        self._m_resets.inc()
        self.bump_epoch()
        if self.host_manager.available_slots() < self.min_np:
            return 1
        if delay > 0:
            # interruptible: stop() must not wait out the backoff
            self._stop.wait(delay)
        return None

    def _terminate(self, alive):
        """Forward SIGTERM to every live worker, wait out the preemption
        grace window (``HOROVOD_PREEMPT_GRACE_S``) so in-flight
        checkpoint flushes and diag dumps can complete, then escalate
        stragglers to SIGKILL — logging each decision with the rank and
        elapsed time, so a kill that raced a flush is attributable."""
        start = time.monotonic()
        for slot, h in alive.values():
            h.terminate()
        grace = env_schema.get_float(env_schema.HOROVOD_PREEMPT_GRACE_S,
                                     15.0)
        deadline = start + grace
        for slot, h in alive.values():
            while h.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            elapsed = time.monotonic() - start
            if h.poll() is None:
                LOG.warning(
                    "elastic: worker rank %d did not exit within the "
                    "%.1fs grace window after SIGTERM (%.1fs elapsed); "
                    "escalating to SIGKILL", slot.rank, grace, elapsed)
                h.kill()
            else:
                LOG.info(
                    "elastic: worker rank %d exited %.1fs after SIGTERM "
                    "(grace window %.1fs)", slot.rank, elapsed, grace)
        alive.clear()

    def stop(self):
        self._stop.set()
        self.rendezvous.stop()


def make_base_env_fn(driver: ElasticDriver, extra: dict,
                     hostname_override: Optional[str] = None,
                     network_interface: Optional[str] = None):
    """Per-slot env factory shared by the CLI elastic path and the Ray
    elastic executor. One coordinator address per round: every slot of a
    round must share it (jax.distributed world bootstrap), and each round
    needs a fresh port — the previous incarnation's coordinator may still
    be tearing down.

    Addressing per round (same route-probe redesign as the static
    launcher, runner/network.py): the rendezvous address is the driver
    address routable from the round's remote hosts (127.0.0.1 when all
    slots are local; ``network_interface`` pins the NIC); the
    jax.distributed coordinator binds on rank 0's host, so its address is
    that host — or the driver address when rank 0 is local."""
    from ..common import env as env_schema
    from ..runner.launch import _free_port, slot_env
    from ..runner.network import is_local_host, pick_coordinator_address

    by_epoch: dict[int, tuple[str, str]] = {}

    def base_env(slot: SlotInfo) -> dict:
        ep = driver._epoch
        if ep not in by_epoch:
            slots = getattr(driver, "current_slots", None) or [slot]
            remote = sorted({s.hostname for s in slots
                             if not is_local_host(s.hostname)})
            if remote:
                addr, _ = pick_coordinator_address(
                    remote, iface_override=network_interface)
            else:
                addr = "127.0.0.1"
            s0 = next((s for s in slots if s.rank == 0), slot)
            coord_host = (addr if is_local_host(s0.hostname)
                          else s0.hostname)
            # _free_port probes on the driver host — best-effort for a
            # remote rank 0 (same limitation as the Ray engine's
            # free_port_on fallback)
            by_epoch[ep] = (addr, f"{coord_host}:{_free_port()}")
        addr, coordinator = by_epoch[ep]
        e = slot_env(slot, addr, driver.rendezvous.port, coordinator, extra)
        if hostname_override is not None:
            e[env_schema.HOROVOD_HOSTNAME] = hostname_override
        return e

    return base_env


def run_elastic(command: list[str], args) -> int:
    """CLI entry (reference launch.py:621 _run_elastic →
    gloo_run_elastic)."""
    import sys
    import tempfile
    import uuid

    from ..runner.launch import (_knob_env, build_ssh_command,
                                 start_output_threads)

    if not args.host_discovery_script:
        raise SystemExit("elastic mode requires --host-discovery-script")
    # job secret must exist before the driver's RendezvousServer starts
    # (the store binds its verification key at construction); slot_env's
    # os.environ snapshot then carries it through every incarnation
    from ..runner.secret import get_or_mint_env_secret

    get_or_mint_env_secret()
    discovery = HostDiscoveryScript(args.host_discovery_script,
                                    default_slots=args.slots_per_host)
    driver = ElasticDriver(discovery, min_np=args.min_np or 1,
                           max_np=args.max_np)
    extra = _knob_env(args)
    # committed-state store for the restart-based recovery model (see class
    # docstring): same path string on every worker, resolved per host-local
    # filesystem. Stable assignment keeps rank 0 on a surviving host, so the
    # restored-then-broadcast state is the authoritative one.
    extra.setdefault(
        "HOROVOD_ELASTIC_STORE",
        os.path.join(tempfile.gettempdir(),
                     f"hvd_elastic_{uuid.uuid4().hex[:8]}.pkl"))

    base_env = make_base_env_fn(
        driver, extra,
        network_interface=getattr(args, "network_interface", None))

    out_dir = getattr(args, "output_filename", None)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    teed_ranks: set[int] = set()

    def create_worker(slot: SlotInfo, env: dict) -> WorkerHandle:
        from ..runner.network import is_local_host

        local = is_local_host(slot.hostname)
        if local:
            cmd = command
        else:
            cmd = build_ssh_command(
                slot.hostname, command, env,
                ssh_port=getattr(args, "ssh_port", None),
                ssh_identity_file=getattr(args, "ssh_identity_file", None))
        if not out_dir:
            p = subprocess.Popen(cmd, env=env if local else None,
                                 stdout=sys.stdout, stderr=sys.stderr)
            return _SubprocessWorker(p)
        # per-rank tee: fresh files on the rank's FIRST incarnation,
        # append across elastic respawns so one file tells the whole
        # story of that rank (reference horovodrun --output-filename)
        p = subprocess.Popen(cmd, env=env if local else None,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE)
        first = slot.rank not in teed_ranks
        teed_ranks.add(slot.rank)
        threads = start_output_threads(p, slot.rank, out_dir,
                                       first_incarnation=first)
        return _SubprocessWorker(p, threads)

    try:
        return driver.run(create_worker, base_env)
    finally:
        driver.stop()
