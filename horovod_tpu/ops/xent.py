"""Chunked softmax cross-entropy — the LM loss without the logits tensor.

The standard LM loss materializes float32 logits [tokens, vocab] — at
seq 32k, vocab 128k that is 16 GiB, usually the single biggest tensor in
long-context training (bigger than any activation once remat is on).
This computes loss and gradients streaming over VOCAB CHUNKS with an
online logsumexp, so peak memory is one [tokens, chunk] block:

- forward: ``lax.scan`` over chunks of the projection matrix; carries
  (running max, rescaled exp-sum, target logit) — the same online
  softmax algebra as flash attention, applied to the classifier.
- backward (custom VJP): a second scan recomputes each logits chunk,
  forms ``dlogits = (softmax - onehot) * ct / N`` for that chunk only,
  and accumulates ``dx`` while emitting per-chunk ``dW`` slices.

Greenfield vs the reference (SURVEY.md §2.3: the reference is a
communication library with no model-side kernels); the technique is the
standard fused/chunked-CE pattern used by TPU LM codebases.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunks(V: int, chunk: int) -> int:
    chunk = min(chunk, V)
    if V % chunk:
        raise ValueError(
            f"vocab size {V} must be divisible by xent chunk {chunk}")
    return V // chunk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def chunked_softmax_xent(x, w, targets, chunk: int = 8192):
    """Mean cross-entropy of ``softmax(x @ w.T)`` against ``targets``.

    x: [N, d] activations; w: [V, d] classifier (embedding) matrix;
    targets: [N] int ids. Returns the scalar mean loss. Differentiable
    in x and w; logits are never materialized beyond [N, chunk].
    """
    loss, _ = _forward(x, w, targets, chunk)
    return loss


def _forward(x, w, targets, chunk: int):
    N, d = x.shape
    V = w.shape[0]
    # mirror the dense path exactly: JAX take_along_axis clamps
    # out-of-range ids, so e.g. -1 padding hits index 0 there — without
    # this the online path would leave tgt at NEG_INF (loss ~1e30) and
    # drop the onehot from the gradient, silently changing training
    targets = jnp.clip(targets, 0, V - 1)
    n_chunks = _chunks(V, chunk)
    xf = x.astype(jnp.float32)
    wc = w.reshape(n_chunks, V // n_chunks, d)

    def body(carry, wi_c):
        m, l, tgt = carry
        wi, c = wi_c
        logits = (xf @ wi.astype(jnp.float32).T)          # [N, C]
        m_new = jnp.maximum(m, logits.max(axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=-1)
        base = c * logits.shape[1]
        local = targets - base
        in_chunk = (local >= 0) & (local < logits.shape[1])
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, logits.shape[1] - 1)[:, None],
            axis=1)[:, 0]
        tgt = jnp.where(in_chunk, picked, tgt)
        return (m_new, l, tgt), None

    init = (jnp.full((N,), NEG_INF, jnp.float32),
            jnp.zeros((N,), jnp.float32),
            jnp.full((N,), NEG_INF, jnp.float32))
    (m, l, tgt), _ = lax.scan(body, init, (wc, jnp.arange(n_chunks)))
    lse = m + jnp.log(l)
    loss = jnp.mean(lse - tgt)
    return loss, (lse,)


def _fwd(x, w, targets, chunk):
    loss, (lse,) = _forward(x, w, targets, chunk)
    return loss, (x, w, targets, lse)


def _bwd(chunk, res, ct):
    x, w, targets, lse = res
    N, d = x.shape
    V = w.shape[0]
    targets = jnp.clip(targets, 0, V - 1)
    n_chunks = _chunks(V, chunk)
    xf = x.astype(jnp.float32)
    wc = w.reshape(n_chunks, V // n_chunks, d)
    scale = ct / N  # d(mean)/d(per-token) — ct is the loss cotangent

    def body(dx, wi_c):
        wi, c = wi_c
        wif = wi.astype(jnp.float32)
        logits = xf @ wif.T                                # [N, C]
        p = jnp.exp(logits - lse[:, None])                 # softmax chunk
        base = c * logits.shape[1]
        local = targets - base
        in_chunk = (local >= 0) & (local < logits.shape[1])
        onehot = (jnp.where(in_chunk, local, -1)[:, None]
                  == jnp.arange(logits.shape[1])[None, :])
        dlogits = (p - onehot.astype(jnp.float32)) * scale
        dx = dx + dlogits @ wif                            # [N, d]
        dwi = dlogits.T @ xf                               # [C, d]
        return dx, dwi

    dx, dwc = lax.scan(body, jnp.zeros((N, d), jnp.float32),
                       (wc, jnp.arange(n_chunks)))
    return (dx.astype(x.dtype), dwc.reshape(V, d).astype(w.dtype), None)


chunked_softmax_xent.defvjp(_fwd, _bwd)
