"""Cross-process controller: negotiation of globally-ready named tensors.

Reference: /root/reference/horovod/common/controller.cc —
`ComputeResponseList` (:69): each cycle, workers send their ready tensor
names to the coordinator (rank 0), which counts submissions
(`IncrementTensorCount` :942), validates dtype/shape/op consistency
(`ConstructResponse` :471-748 — mismatches become ERROR responses), orders
and fuses ready tensors, and broadcasts the response list everyone must
execute (`SendFinalTensors`).

TPU-shaped differences:

- Transport is the launcher's rendezvous HTTP KV store (the reference's
  Gloo controller equally rides the launcher's HTTP store for bootstrap;
  here it carries the negotiation itself — negligible traffic: names, not
  tensors). Wire format is JSON (the role of the FlatBuffers schema,
  common/wire/message.fbs: a size-stable, language-neutral encoding — JSON
  chosen because the C++ side of this runtime is not built yet).
- Only *eager async* ops negotiate. Compiled SPMD programs are symmetric
  by construction and never enter this path — the negotiation protocol
  survives exactly where dynamism is real (SURVEY.md §7 hard part 1).
- The response carries the coordinator's submission order; every process
  derives identical fusion groups from it locally (same deterministic
  algorithm), replacing FuseResponses' look-ahead (:777-849).

Protocol (round r, scope ``ctl``):
  worker k:  PUT  ctl/r{r}/ready/{k}   = JSON [ [name, sig], ... ]
  rank 0:    GET  ctl/r{r}/ready/* (all k) → count/validate/order
             PUT  ctl/r{r}/resp        = JSON {"ready": [names...],
                                               "errors": {name: msg}}
  worker k:  GET  ctl/r{r}/resp (blocking) → execute / fail
Rounds advance in lockstep; scope r-2 is garbage-collected by rank 0.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Optional

LOG = logging.getLogger("horovod_tpu")


def entry_signature(entry) -> list:
    """Consistency-checked fields (reference ConstructResponse checks
    dtype :538, op :548, shape :596, devices :619).

    Metadata only — reads .shape/.dtype attributes, never materializes the
    tensor (a device array must not be copied to host once per cycle just
    to describe it). Cached on the entry: signatures are immutable.
    """
    cached = getattr(entry, "_sig", None)
    if cached is not None:
        return cached
    t = entry.tensor
    shape = list(getattr(t, "shape", []))
    dtype = str(getattr(t, "dtype", type(t).__name__))
    sig = [entry.op, dtype, shape, int(entry.reduce_op),
           entry.root_rank, float(entry.prescale_factor),
           float(entry.postscale_factor)]
    entry._sig = sig
    return sig


class KVController:
    """One instance per process; rank 0 additionally runs the coordinator
    loop in a background thread."""

    # Worker waits for the response strictly longer than the coordinator
    # waits for a straggling rank (STRAGGLER_TIMEOUT retry loop below), so a
    # slow rank stalls the round, never desyncs it.
    RESPONSE_TIMEOUT_S = 300.0

    def __init__(self, client, rank: int, size: int,
                 poll_timeout: float = RESPONSE_TIMEOUT_S):
        self.client = client
        self.rank = rank
        self.size = size
        self.round = 0
        self.poll_timeout = poll_timeout
        self.broken = False
        self._coord: Optional[_Coordinator] = None
        if rank == 0:
            self._coord = _Coordinator(client, size)
            self._coord.start()

    def negotiate(self, pending: dict[str, list]) -> tuple[list[str], dict[str, str]]:
        """Submit this process's ready set; return (ordered ready names,
        per-name errors). Blocks for the round's response.

        Any failure marks the controller broken: a worker that missed a
        round can never rejoin the lockstep safely (other ranks may have
        executed collectives it skipped), so the only sound recovery is the
        reference's — surface HorovodInternalError and let elastic mode
        re-initialize the world (common/elastic.py:151 semantics).
        """
        if self.broken:
            raise RuntimeError("controller is broken; re-initialize horovod_tpu")
        r = self.round
        try:
            payload = json.dumps([[n, sig] for n, sig in pending.items()]).encode()
            self.client.put(f"ctl/r{r}", f"ready/{self.rank}", payload)
            resp = json.loads(self.client.get(f"ctl/r{r}", "resp",
                                              timeout=self.poll_timeout))
        except Exception:
            self.broken = True
            raise
        self.round += 1
        return resp["ready"], resp.get("errors", {})

    def stop(self):
        if self._coord:
            self._coord.stop()


class _Coordinator(threading.Thread):
    """Rank-0 aggregation loop (the MessageTable owner, controller.h:35)."""

    def __init__(self, client, size: int):
        super().__init__(daemon=True, name="hvd-coordinator")
        self.client = client
        self.size = size
        self._stop_evt = threading.Event()
        # name -> (sig, set of ranks that submitted) — persists across
        # rounds like the reference's message_table_
        self.table: dict[str, tuple[list, set[int]]] = {}
        self.order: list[str] = []  # rank-0-submission-order tie break
        self.errors: dict[str, str] = {}

    # per-rank wait per attempt; transient misses retry until stop —
    # a rank stuck in a long XLA compile must stall the round, not kill the
    # coordinator (the reference tolerates stalls and only *warns*,
    # stall_inspector.h:39)
    STRAGGLER_TIMEOUT_S = 30.0

    def _get_with_retry(self, scope: str, key: str) -> Optional[bytes]:
        while not self._stop_evt.is_set():
            try:
                return self.client.get(scope, key,
                                       timeout=self.STRAGGLER_TIMEOUT_S)
            except Exception:
                continue  # straggler: keep waiting for this rank
        return None

    def run(self):
        r = 0
        while not self._stop_evt.is_set():
            try:
                for k in range(self.size):
                    raw = self._get_with_retry(f"ctl/r{r}", f"ready/{k}")
                    if raw is None:
                        return  # stopping
                    for name, sig in json.loads(raw):
                        self._increment(name, sig, k)
                ready = [n for n in self.order
                         if len(self.table[n][1]) == self.size]
                errors = {n: self.errors[n] for n in list(self.errors)}
                for n in ready:
                    del self.table[n]
                    self.order.remove(n)
                for n in errors:
                    self.table.pop(n, None)
                    if n in self.order:
                        self.order.remove(n)
                    self.errors.pop(n, None)
                self.client.put(f"ctl/r{r}", "resp",
                                json.dumps({"ready": ready,
                                            "errors": errors}).encode())
                if r >= 2:
                    self.client.delete_scope(f"ctl/r{r - 2}")
                r += 1
            except Exception as e:
                if self._stop_evt.is_set():
                    return
                LOG.warning("coordinator round %d error: %s", r, e)
                return

    def _increment(self, name: str, sig: list, rank: int):
        """IncrementTensorCount + mismatch validation (controller.cc:942,
        :471-748)."""
        if name not in self.table:
            self.table[name] = (sig, {rank})
            self.order.append(name)
            return
        ref_sig, ranks = self.table[name]
        if sig != ref_sig:
            self.errors[name] = (
                f"Mismatched submissions for tensor {name!r}: rank {rank} "
                f"sent {sig}, previously {ref_sig} (reference "
                "controller.cc:538-619 semantics)")
            return
        ranks.add(rank)

    def stop(self):
        self._stop_evt.set()
