"""Cross-process controller: negotiation of globally-ready named tensors.

Reference: /root/reference/horovod/common/controller.cc —
`ComputeResponseList` (:69): each cycle, workers send their ready tensor
names to the coordinator (rank 0), which counts submissions
(`IncrementTensorCount` :942), validates dtype/shape/op consistency
(`ConstructResponse` :471-748 — mismatches become ERROR responses), orders
and fuses ready tensors, and broadcasts the response list everyone must
execute (`SendFinalTensors`).

TPU-shaped differences:

- Transport is the launcher's rendezvous HTTP KV store (the reference's
  Gloo controller equally rides the launcher's HTTP store for bootstrap;
  here it carries the negotiation itself — negligible traffic: names, not
  tensors). Wire format is JSON (the role of the FlatBuffers schema,
  common/wire/message.fbs: a size-stable, language-neutral encoding — JSON
  chosen because the C++ side of this runtime is not built yet).
- Only *eager async* ops negotiate. Compiled SPMD programs are symmetric
  by construction and never enter this path — the negotiation protocol
  survives exactly where dynamism is real (SURVEY.md §7 hard part 1).
- The response carries the coordinator's submission order; every process
  derives identical fusion groups from it locally (same deterministic
  algorithm), replacing FuseResponses' look-ahead (:777-849).

Protocol (round r; P = ctl/e{epoch}g{gen}, the generation prefix — epoch
from the elastic driver's incarnation, gen from in-process reinits):
  worker k:  PUT  P/r{r}/ready/{k}   = JSON {"e": [[name, sig], ...],
                                             "j": joined?}
             (or the 1-byte SAME_AS_LAST marker when identical to round r-1)
  rank 0:    GET  P/r{r}/ready/* (all k) → count/validate/order
             PUT  P/r{r}/resp        = JSON {"ready": [names...],
                                             "sigs": {name: sig},
                                             "errors": {name: msg},
                                             "join_done": last_rank|null}
  worker k:  GET  P/r{r}/resp (blocking) → execute / fail
Rounds advance in lockstep; scope r-2 is garbage-collected by rank 0, and
a starting coordinator purges every dead generation under ctl/ (its own
prefix excluded).

Scale-out mode (HOROVOD_HIER_NEGOTIATION, docs/scaling.md): workers
advertise wire v2 in their round-0 submission ("wv": 2); when EVERY rank
advertised it the coordinator confirms in the round-0 response and from
round 1 on the payloads are the compact binary frames of ops/wire.py and
ranks submit through a deterministic per-group leader (rank // k * k),
which merges the group into one rank-bitmap aggregate
(P/r{r}/ready/g{gid}) and fans the coordinator's response back down
(P/r{r}/g{gid}/resp). A missing/slow leader is survived per round: the
member re-submits flat after HOROVOD_HIER_FALLBACK_S and stays flat for
a backoff window, so coordinator fan-in degrades from O(N/k) back toward
O(N) but no round is ever lost. Mixed worlds (any rank without "wv")
stay on v1, and with the flag off the wire is byte-identical to v1.

Join semantics (reference JoinOp, collective_operations.h:271 +
global_state.h:107-111 "joined ranks contribute zeros"): a joined rank keeps
negotiating with ``j=true`` and counts as an implicit submitter for every
tensor; the response's ``sigs`` let it fabricate a zero contribution of the
right shape/dtype so the SPMD eager collective still runs everywhere. When
every rank has joined, ``join_done`` carries the last rank to join and the
joined state resets.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from ..common import env as env_schema
from ..utils import diag as diag_mod
from ..utils import faults as faults_mod
from ..utils import flightrec as flightrec_mod
from ..utils import lockcheck
from ..utils import metrics as metrics_mod
from ..utils import retry as retry_mod
from ..utils import tracing as tracing_mod
from . import megaplan as megaplan_mod
from . import wire as wire_mod

LOG = logging.getLogger("horovod_tpu")

#: First byte of every v2 binary frame (sniffed against raw payloads —
#: v1 JSON starts with ``{``/``[`` and the marker with ``=``).
_MAGIC_BYTE = bytes((wire_mod.MAGIC_V2,))


def _ctl_prefix() -> str:
    """Namespace for this controller generation's rounds.

    Two components: the elastic incarnation (HOROVOD_ELASTIC_EPOCH —
    bumped by the driver on restart-based recovery) and the in-process
    reinit generation (HOROVOD_ELASTIC_GEN — bumped by
    elastic._reinitialize on HorovodInternalError recovery without a
    relaunch). A new lockstep must never read a dead generation's rounds:
    its ctl/.../r0 keys are still in the launcher's store and a stale
    `resp` silently desyncs the new world (found by the end-to-end
    crash-restart test). Ranks whose generation counters diverge starve
    (nobody serves their scope), hit their response timeout, and reinit
    again — converging on the highest generation.
    """
    return (f"ctl/e{os.environ.get(env_schema.HOROVOD_ELASTIC_EPOCH, '0')}"
            f"g{os.environ.get(env_schema.HOROVOD_ELASTIC_GEN, '0')}")


def _ctl_scope(r: int) -> str:
    return f"{_ctl_prefix()}/r{r}"


def _source_order(suffix: str):
    """Deterministic processing order for a round's submission sources:
    flat ranks first (numeric), then leader aggregates ("g<id>"); None
    for foreign keys under the ready/ prefix (skipped, as v1 skipped
    non-integer suffixes)."""
    if suffix.isdigit():
        return (0, int(suffix))
    if suffix[:1] == "g" and suffix[1:].isdigit():
        return (1, int(suffix[1:]))
    return None


def entry_signature(entry) -> list:
    """Consistency-checked fields (reference ConstructResponse checks
    dtype :538, op :548, shape :596, devices :619; process-set identity is
    part of the request key in post-v0.21 Horovod).

    Metadata only — reads .shape/.dtype attributes, never materializes the
    tensor (a device array must not be copied to host once per cycle just
    to describe it). Cached on the entry: signatures are immutable.
    """
    cached = getattr(entry, "_sig", None)
    if cached is not None:
        return cached
    t = entry.tensor
    shape = list(getattr(t, "shape", []))
    # allgather/alltoall are ragged in the first dimension by contract
    # (reference controller.cc:596: "all dimensions, except the first,
    # must be the same"), so the first dim is not consistency-checked
    if entry.op in ("allgather", "alltoall") and shape:
        shape[0] = "*"
    dtype = str(getattr(t, "dtype", type(t).__name__))
    ps = getattr(entry, "process_set", None)
    ps_name = getattr(ps, "name", None) or "global"
    # Eager tensors are host-resident at enqueue; the consistency-relevant
    # device identity is the platform the collective will execute on
    # (reference controller.cc:619 validates CPU-vs-GPU placement).
    dev = getattr(getattr(t, "sharding", None), "memory_kind", None) or "host"
    sig = [entry.op, dtype, shape, int(entry.reduce_op),
           entry.root_rank, float(entry.prescale_factor),
           float(entry.postscale_factor), ps_name, str(dev)]
    if ps is not None and ps_name != "global" \
            and getattr(ps, "_proc_indices", None) is not None:
        # readiness is scoped to the set's member processes (reference:
        # each ProcessSet owns its own controller/message table) — carry
        # their GLOBAL cross-ranks so the coordinator knows who must
        # submit. Deliberately carried IN the signature rather than
        # resolved from the coordinator's registry: a worker may create
        # the set and submit before rank 0's add_process_set runs, and
        # SAME_AS_LAST makes the per-round byte cost a one-time hit
        from ..common import context as ctx_mod

        gprocs = ctx_mod.global_process_set()._proc_indices
        sig.append(sorted(gprocs.index(p)
                          for p in set(ps._proc_indices)))
    entry._sig = sig
    return sig


class KVController:
    """One instance per process; rank 0 additionally runs the coordinator
    loop in a background thread."""

    # Worker waits for the response strictly longer than the coordinator
    # waits for a straggling rank (STRAGGLER_TIMEOUT retry loop below), so a
    # slow rank stalls the round, never desyncs it.
    RESPONSE_TIMEOUT_S = 300.0

    # Per-attempt server-side block while polling for the round response.
    # The overall RESPONSE_TIMEOUT_S budget is spent as bounded re-polls
    # with backoff (utils/retry.py) instead of one flat blocking GET: a
    # store blip or dropped socket mid-wait costs one re-poll, not the
    # whole round, and the worker's liveness is observable per attempt
    # (hvd_retry_attempts_total{site="controller.poll"}).
    POLL_ATTEMPT_S = 10.0

    # Marker payload for the steady-state fast path: "my submitted set is
    # identical to last round's". The moral of the reference response cache's
    # bitvector sync (response_cache.h:45, controller.cc:139-237): repeated
    # signature sets cost one cached-state bit per rank instead of a
    # re-serialized, re-validated message list.
    SAME_AS_LAST = b"="

    on_params = None  # callable(dict) applied at response receipt

    # Megaplan replay lease (ops/megaplan.py): True while the coordinator
    # granted "mp" on the latest response — every rank has been sending
    # SAME_AS_LAST markers for the stability window, so whole-step replay
    # may enter/exit at the same round boundary on every rank. Updated by
    # _finish_round each round; read by the cycle loop's capture gate.
    megaplan_lease = False

    # After a leader let a member (or its own merge) down, ranks submit
    # flat for this many rounds before re-trying the hierarchy — a dead
    # leader must not cost a fallback timeout every round, and the whole
    # group re-converges on the same round (everyone backs off from the
    # round that failed).
    FLAT_BACKOFF_ROUNDS = 16

    def __init__(self, client, rank: int, size: int,
                 poll_timeout: float = RESPONSE_TIMEOUT_S,
                 stall_warning_s: float = 60.0,
                 stall_shutdown_s: float = 0.0,
                 hier: Optional[bool] = None,
                 hier_group_size: Optional[int] = None,
                 hier_fallback_s: Optional[float] = None):
        self.client = client
        self.rank = rank
        self.size = size
        self.round = 0
        self.poll_timeout = poll_timeout
        self.broken = False
        self._last_payload: Optional[bytes] = None
        # observability: wire bytes + fast-path round count (testable proxy
        # for "negotiation cost is O(1) in steady state")
        self.bytes_sent = 0
        self.bytes_received = 0
        self.fast_rounds = 0
        # hierarchical scale-out (docs/scaling.md) — until the round-0
        # version handshake completes, everything below is dormant and the
        # v1 wire is byte-identical to a build without this code
        if hier is None:
            hier = env_schema.get_bool(env_schema.HOROVOD_HIER_NEGOTIATION)
        self._hier = bool(hier)
        k = (hier_group_size if hier_group_size is not None
             else env_schema.get_int(env_schema.HOROVOD_HIER_GROUP_SIZE, 8))
        self._group_size = max(1, int(k))
        self._fallback_s = float(
            hier_fallback_s if hier_fallback_s is not None
            else env_schema.get_float(env_schema.HOROVOD_HIER_FALLBACK_S,
                                      5.0))
        self._group = rank // self._group_size
        self._group_ranks = list(range(
            self._group * self._group_size,
            min((self._group + 1) * self._group_size, size)))
        self._member_set = set(self._group_ranks)
        self._wire_version = 1
        self._resp_dec: Optional[wire_mod.ResponseDecoder] = None
        self._last_channel = "flat"  # which cache holds _last_payload
        self._last_agg: Optional[bytes] = None
        self._member_cache: dict[int, dict] = {}  # leader-side marker cache
        self._flat_until = 0
        self._m_wire_v2: dict = {}  # direction -> labeled counter, lazy
        reg = metrics_mod.get_registry()
        # cache hit = SAME_AS_LAST marker round (the response-cache role);
        # miss = a full re-serialized payload
        self._m_cache_hit = reg.counter(
            "hvd_controller_cache_hits_total",
            "negotiation rounds sent as the 1-byte SAME_AS_LAST marker")
        self._m_cache_miss = reg.counter(
            "hvd_controller_cache_misses_total",
            "negotiation rounds sent as a full payload")
        self._m_wire_bytes = reg.counter(
            "hvd_controller_wire_bytes_total",
            "negotiation submission bytes put to the KV store")
        # cross-rank tracing: when on, each submission carries this rank's
        # clock-aligned submit time so the coordinator can attribute
        # stragglers; when off, the wire format is byte-identical to the
        # untraced build (zero-cost contract)
        self._tracer = tracing_mod.get_tracer()
        self._coord: Optional[_Coordinator] = None
        if rank == 0:
            self._coord = _Coordinator(client, size,
                                       stall_warning_s=stall_warning_s,
                                       stall_shutdown_s=stall_shutdown_s)
            self._coord.start()

    def set_group_size(self, k: int):
        """Adopt a new hierarchical group size (autotuner knob). Called
        from ``on_params`` at response receipt — every rank applies it at
        the same round boundary, so the recomputed groups agree before
        the next round's submission. All per-channel caches are dropped
        (channel re-handshake): a SAME_AS_LAST marker, a leader-side
        member cache, or an aggregate payload from the old grouping must
        never be replayed against the new channels."""
        k = max(1, int(k))
        if k == self._group_size:
            return
        self._group_size = k
        self._group = self.rank // k
        self._group_ranks = list(range(
            self._group * k, min((self._group + 1) * k, self.size)))
        self._member_set = set(self._group_ranks)
        self._member_cache.clear()
        self._last_payload = None
        self._last_agg = None
        self._last_channel = "flat"
        self._flat_until = 0
        # new grouping = new submission channels: a captured whole-step
        # schedule keyed to the old round topology must not replay
        megaplan_mod.invalidate_megaplan("hier_group")

    def negotiate(self, pending: dict[str, list],
                  joined: bool = False,
                  shutting_down: bool = False) -> dict:
        """Submit this process's ready set; return the round response dict
        (``ready`` ordered names, ``errors`` per-name, ``sigs`` for ready
        names, ``join_done`` last-joined rank or None). Blocks for the
        round's response.

        Any failure marks the controller broken: a worker that missed a
        round can never rejoin the lockstep safely (other ranks may have
        executed collectives it skipped), so the only sound recovery is the
        reference's — surface HorovodInternalError and let elastic mode
        re-initialize the world (common/elastic.py:151 semantics).
        """
        if self.broken:
            raise RuntimeError("controller is broken; re-initialize horovod_tpu")
        r = self.round
        try:
            if self._wire_version >= wire_mod.WIRE_V2:
                raw = self._round_v2(r, pending, joined, shutting_down)
                self._wire_count("rx", len(raw))
            else:
                raw = self._round_v1(r, pending, joined, shutting_down)
            self.bytes_received += len(raw)
            resp = self._decode_response(raw)
        except Exception:
            self.broken = True
            raise
        return self._finish_round(resp)

    def lease_round(self) -> dict:
        """One replay-mode round: the megaplan lease is held, so this
        process's submission is — by the captured signature's guarantee —
        identical to last round's, and the round submits the verbatim
        1-byte SAME_AS_LAST marker without re-serializing anything (the
        Python-free steady state of docs/performance.md). The response
        still flows through the full `_finish_round` control path, so
        params pushes, aborts, cache invalidations and shutdown are never
        lost in replay mode; the caller re-checks ``megaplan_lease`` (and
        the megaplan epoch) on return and degrades when the coordinator
        dropped the grant mid-round. Wire v1 only: the coordinator never
        grants the lease under the hierarchical v2 wire, whose leaders
        must still merge member submissions every round."""
        if self.broken:
            raise RuntimeError("controller is broken; re-initialize horovod_tpu")
        r = self.round
        try:
            w = self.SAME_AS_LAST
            if self._tracer is not None:
                w += json.dumps({"t": self._tracer.aligned_now()}).encode()
            self.fast_rounds += 1
            self._m_cache_hit.inc()
            faults_mod.fault_point("controller.submit")
            self.client.put(_ctl_scope(r), f"ready/{self.rank}", w)
            self.bytes_sent += len(w)
            self._m_wire_bytes.inc(len(w))
            raw = self._poll_response(r)
            self.bytes_received += len(raw)
            resp = self._decode_response(raw)
        except Exception:
            self.broken = True
            raise
        return self._finish_round(resp)

    def _finish_round(self, resp: dict) -> dict:
        """Shared response-processing tail of `negotiate` and
        `lease_round`: the round's control effects (abort, lockstep
        advance, cache invalidation, lease state, shutdown, tuned params,
        wire handshake) apply identically in negotiated and replay mode."""
        if resp.get("abort"):
            # coordinator died and fail-fast-closed the round: this
            # controller can never rejoin the lockstep
            self.broken = True
            raise RuntimeError(resp["abort"])
        self.round += 1
        if resp.get("invalidate"):
            # coordinator dropped its submission cache (error-closed
            # round): the next round must carry a full payload
            self._last_payload = None
            self._last_agg = None
        resp.setdefault("errors", {})
        resp.setdefault("sigs", {})
        resp.setdefault("join_done", None)
        # replay lease: granted (or re-granted) per round; any round the
        # coordinator does not grant it drops every rank out of replay at
        # the same boundary
        self.megaplan_lease = bool(resp.get("mp"))
        if resp.get("shutdown_done"):
            # every rank has requested shutdown: the lockstep is over
            self.broken = True
        if resp.get("params") is not None and self.on_params is not None:
            # reference SynchronizeParameters (controller.cc:39-53): tuned
            # knobs ride the response, so every rank applies them at the
            # same point relative to the round's collectives — an
            # asynchronously-applied hierarchical flag would make ranks
            # build DIFFERENT programs for the same negotiated tensor
            try:
                self.on_params(resp["params"])
            except Exception as e:  # tuning must never break the lockstep
                LOG.warning("on_params failed: %s", e)
        if (self._wire_version < wire_mod.WIRE_V2
                and int(resp.get("wv") or 1) >= wire_mod.WIRE_V2):
            # round-0 handshake complete: every rank advertised v2 and the
            # coordinator confirmed — binary frames + hierarchy from the
            # next round. Fresh caches: markers never cross wire formats.
            self._wire_version = wire_mod.WIRE_V2
            self._resp_dec = wire_mod.ResponseDecoder()
            self._last_payload = None
            self._last_agg = None
        return resp

    def _round_v1(self, r: int, pending: dict, joined: bool,
                  shutting_down: bool) -> bytes:
        """Legacy flat JSON round — byte-identical to the pre-hierarchy
        wire except for the one-time round-0 ``"wv"`` version advert
        (present only when HOROVOD_HIER_NEGOTIATION is on)."""
        # the base payload (no timestamp) is what the SAME_AS_LAST
        # comparison sees: a per-round submit time must not break the
        # 1-byte steady-state fast path
        base = {"e": [[n, sig] for n, sig in pending.items()],
                "j": bool(joined), "sd": bool(shutting_down)}
        if self._hier and r == 0:
            base["wv"] = wire_mod.WIRE_V2
        payload = json.dumps(base).encode()
        t_sub = (self._tracer.aligned_now()
                 if self._tracer is not None and pending else None)
        if payload == self._last_payload:
            # fast round; with tracing on, the marker carries a tiny
            # timestamp suffix the coordinator strips (still O(1) and
            # signature-free — the cached submission decodes the set)
            w = self.SAME_AS_LAST
            if t_sub is not None:
                w += json.dumps({"t": t_sub}).encode()
            self.fast_rounds += 1
            self._m_cache_hit.inc()
        else:
            w = payload
            if t_sub is not None:
                w = json.dumps(dict(base, t=t_sub)).encode()
            self._m_cache_miss.inc()
        faults_mod.fault_point("controller.submit")
        self.client.put(_ctl_scope(r), f"ready/{self.rank}", w)
        self.bytes_sent += len(w)
        self._m_wire_bytes.inc(len(w))
        self._last_payload = payload
        return self._poll_response(r)

    # -- wire v2 / hierarchical rounds ------------------------------------

    def _decode_response(self, raw: bytes) -> dict:
        """Sniff the response frame: v2 binary when it is one, else the
        v1 JSON shapes (normal, error-close, abort — the coordinator
        keeps failure responses in JSON in every mode, so they never
        carry interning state a broken world could lose)."""
        if raw[:1] == _MAGIC_BYTE and self._resp_dec is not None:
            return self._resp_dec.decode(raw)
        return json.loads(raw)

    def _wire_count(self, direction: str, n: int) -> None:
        c = self._m_wire_v2.get(direction)
        if c is None:
            c = self._m_wire_v2[direction] = \
                metrics_mod.get_registry().counter(
                    "hvd_controller_wire_bytes_total",
                    "negotiation submission bytes put to the KV store",
                    direction=direction, format="v2")
        c.inc(n)

    def _sent(self, w: bytes) -> None:
        self.bytes_sent += len(w)
        self._wire_count("tx", len(w))

    @property
    def wire_format(self) -> str:
        """"v1" or "v2" — what this controller currently speaks."""
        return "v2" if self._wire_version >= wire_mod.WIRE_V2 else "v1"

    def _round_v2(self, r: int, pending: dict, joined: bool,
                  shutting_down: bool) -> bytes:
        entries = [(n, sig) for n, sig in pending.items()]
        t_sub = (self._tracer.aligned_now()
                 if self._tracer is not None and pending else None)
        if self.rank == self._group_ranks[0]:
            return self._leader_round(r, entries, joined, shutting_down,
                                      t_sub)
        if r < self._flat_until:
            return self._flat_round(r, entries, joined, shutting_down, t_sub)
        return self._member_round(r, entries, joined, shutting_down, t_sub)

    def _flat_round(self, r: int, entries, joined, shutting_down,
                    t_sub) -> bytes:
        """v2-framed submission straight to the coordinator — the
        fallback topology (and the leader's own path while backed off)."""
        payload = wire_mod.encode_submission(entries, joined, shutting_down)
        if payload == self._last_payload and self._last_channel == "flat":
            w = self.SAME_AS_LAST
            if t_sub is not None:
                w += json.dumps({"t": t_sub}).encode()
            self.fast_rounds += 1
            self._m_cache_hit.inc()
        else:
            w = (payload if t_sub is None else
                 wire_mod.encode_submission(entries, joined, shutting_down,
                                            t=t_sub))
            self._m_cache_miss.inc()
        faults_mod.fault_point("controller.submit")
        self.client.put(_ctl_scope(r), f"ready/{self.rank}", w)
        self._sent(w)
        self._last_payload = payload
        self._last_channel = "flat"
        return self._poll_response(r)

    def _member_round(self, r: int, entries, joined, shutting_down,
                      t_sub) -> bytes:
        """Submit through the group leader; fall back to a flat round if
        the fan-down response never comes (leader dead or wedged)."""
        gscope = f"{_ctl_scope(r)}/g{self._group}"
        payload = wire_mod.encode_submission(entries, joined, shutting_down)
        if payload == self._last_payload and self._last_channel == "group":
            w = self.SAME_AS_LAST
            if t_sub is not None:
                w += json.dumps({"t": t_sub}).encode()
            self.fast_rounds += 1
            self._m_cache_hit.inc()
        else:
            w = (payload if t_sub is None else
                 wire_mod.encode_submission(entries, joined, shutting_down,
                                            t=t_sub))
            self._m_cache_miss.inc()
        faults_mod.fault_point("controller.submit")
        deadline = min(self._fallback_s, self.poll_timeout)
        put_get = getattr(self.client, "put_get", None)
        try:
            if put_get is not None:
                # one exchange: submit + park on the fan-down key (the
                # control plane is exchange-count-bound at pod scale)
                raw = put_get(gscope, f"ready/{self.rank}", w, "resp",
                              timeout=deadline)
            else:
                self.client.put(gscope, f"ready/{self.rank}", w)
                raw = self.client.get(gscope, "resp", timeout=deadline)
            self._sent(w)
            self._last_payload = payload
            self._last_channel = "group"
            return raw
        except Exception:
            # leader suspect: resubmit flat so the round cannot lose this
            # rank's tensors, and stay flat for a backoff window
            self._flat_until = r + self.FLAT_BACKOFF_ROUNDS
            self._last_payload = None
            rec = flightrec_mod.get_recorder()
            if rec is not None:
                rec.note("leader_round", role="member", round=r,
                         group=self._group, fallback=True)
            raw = self._flat_round(r, entries, joined, shutting_down, t_sub)
            # the coordinator may have closed the round off the leader's
            # aggregate without ever reading the flat resubmission, so its
            # flat cache for this rank is not trustworthy yet: markers
            # resume only after a clean flat round
            self._last_payload = None
            return raw

    def _leader_round(self, r: int, entries, joined, shutting_down,
                      t_sub) -> bytes:
        """Gather the group, PUT one aggregate to the coordinator, fan
        the response back down. Any merge/submit failure degrades to a
        flat round (members re-submit flat on their own timeout), so a
        chaos-killed leader stalls a round but never desyncs it."""
        if r < self._flat_until:
            return self._flat_round(r, entries, joined, shutting_down, t_sub)
        gscope = f"{_ctl_scope(r)}/g{self._group}"
        members = self._group_ranks[1:]
        raw = None
        try:
            w, covered = self._merge_group(r, gscope, members, entries,
                                           joined, shutting_down, t_sub)
            faults_mod.fault_point("controller.submit")
            put_get = getattr(self.client, "put_get", None)
            if put_get is not None:
                # submit the aggregate and park on the response in one
                # exchange; a 404 deadline means the PUT landed and the
                # round is just not closed yet — keep polling plainly
                try:
                    raw = put_get(
                        _ctl_scope(r), f"ready/g{self._group}", w, "resp",
                        timeout=max(0.1, min(self.POLL_ATTEMPT_S,
                                             self.poll_timeout / 4.0)))
                except Exception as e:
                    if getattr(e, "code", None) != 404:
                        raise
            else:
                self.client.put(_ctl_scope(r), f"ready/g{self._group}", w)
            self._sent(w)
        except Exception:
            self._last_agg = None
            self._last_payload = None
            self._flat_until = r + self.FLAT_BACKOFF_ROUNDS
            rec = flightrec_mod.get_recorder()
            if rec is not None:
                rec.note("leader_round", role="leader", round=r,
                         group=self._group, fallback=True)
            raw = self._flat_round(r, entries, joined, shutting_down, t_sub)
            self._last_payload = None
            return raw
        if members and len(covered) == 1:
            # no member made it into the aggregate: they are flat (or
            # gone) — stop burning the gather deadline every round and
            # re-converge with their backoff window
            self._flat_until = r + self.FLAT_BACKOFF_ROUNDS
        if raw is None:
            raw = self._poll_response(r)
        if members:
            # members are parked on the group resp key: fan down before
            # local processing so they unblock first
            self.client.put(gscope, "resp", raw)
            self._sent(raw)
        rec = flightrec_mod.get_recorder()
        if rec is not None:
            rec.note("leader_round", role="leader", round=r,
                     group=self._group, covered=len(covered), bytes=len(w))
        return raw

    def _merge_group(self, r: int, gscope: str, members, entries,
                     joined, shutting_down, t_sub):
        """Collect member submissions (partial results after the
        fallback deadline are fine — an uncovered member re-submits flat
        on its own), merge them with this leader's set, and return
        ``(wire_bytes, covered_ranks)``. The aggregate gets the same
        SAME_AS_LAST treatment as a flat payload: byte-deterministic
        encoding compared against last round's."""
        got: dict[int, bytes] = {}
        if members:
            try:
                raw_map = self.client.get_prefix(
                    gscope, "ready/", min_count=len(members),
                    timeout=min(self._fallback_s, self.poll_timeout))
            except Exception:
                raw_map = {}
            for suffix, raw in raw_map.items():
                try:
                    k = int(suffix)
                except ValueError:
                    continue  # foreign key under the prefix
                if k != self.rank and k in self._member_set:
                    got[k] = raw
        faults_mod.fault_point("leader.merge")
        merged: dict = {}  # (name, canonical sig) -> [name, sig, ranks]
        order: list = []
        covered = {self.rank}
        j_set = {self.rank} if joined else set()
        sd_set = {self.rank} if shutting_down else set()
        t_map = {} if t_sub is None else {self.rank: t_sub}

        def add(name, sig, k):
            key = (name, json.dumps(sig))
            ent = merged.get(key)
            if ent is None:
                merged[key] = [name, sig, {k}]
                order.append(key)
            else:
                ent[2].add(k)

        for name, sig in entries:
            add(name, sig, self.rank)
        for k in sorted(got):
            raw = got[k]
            t_k = None
            if raw[:1] == self.SAME_AS_LAST:
                msg = self._member_cache.get(k)
                if msg is None:
                    # nothing cached to expand the marker with: leave the
                    # rank uncovered — it flat-falls-back when the group
                    # resp never frees it (never claim ranks we cannot
                    # actually decode)
                    continue
                if len(raw) > 1:
                    try:
                        t_k = float(json.loads(raw[1:])["t"])
                    except (ValueError, TypeError, KeyError):
                        t_k = None
            else:
                try:
                    msg = wire_mod.decode_submission(raw)
                except wire_mod.WireDecodeError:
                    continue  # torn frame: uncovered, member re-sends flat
                t_k = msg.pop("t", None)
                self._member_cache[k] = msg
            covered.add(k)
            if msg.get("j"):
                j_set.add(k)
            if msg.get("sd"):
                sd_set.add(k)
            if t_k is not None:
                t_map[k] = float(t_k)
            for name, sig in msg.get("e", []):
                add(name, sig, k)
        items = [tuple(merged[key]) for key in order]
        base = wire_mod.encode_aggregate(self._group, self.size, items,
                                         covered, j_set, sd_set)
        if base == self._last_agg:
            w = self.SAME_AS_LAST
            if t_map:
                w += json.dumps(
                    {"t": {str(k): v for k, v in t_map.items()}}).encode()
            self.fast_rounds += 1
            self._m_cache_hit.inc()
        else:
            w = (base if not t_map else
                 wire_mod.encode_aggregate(self._group, self.size, items,
                                           covered, j_set, sd_set,
                                           t_map=t_map))
            self._m_cache_miss.inc()
        self._last_agg = base
        return w, covered

    def _poll_response(self, r: int) -> bytes:
        """Block for round ``r``'s response under the unified retry
        policy: short server-side blocking GETs (POLL_ATTEMPT_S each)
        re-polled with full-jitter backoff until ``poll_timeout``
        expires. Replaces the round-1 flat 300 s GET — same overall
        deadline and the same exception surface at exhaustion (the last
        404/connection error re-raises, marking the controller broken in
        ``negotiate``), but a transient store fault mid-wait now costs
        one re-poll instead of the round."""
        deadline = self.poll_timeout
        start = time.monotonic()
        policy = retry_mod.RetryPolicy(
            max_attempts=None, deadline_s=deadline,
            base_delay_s=0.05, max_delay_s=1.0)

        def attempt():
            faults_mod.fault_point("controller.poll")
            remaining = deadline - (time.monotonic() - start)
            # the deadline/4 term keeps short budgets (tests, tuned-down
            # HOROVOD_RESPONSE_TIMEOUT_S) genuinely re-polling instead of
            # one flat blocking GET that eats the whole budget
            per = max(0.1, min(self.POLL_ATTEMPT_S, deadline / 4.0,
                               remaining))
            return self.client.get(_ctl_scope(r), "resp", timeout=per)

        return retry_mod.Retrier("controller.poll", policy).call(attempt)

    def drain_shutdown(self):
        """Reference shutdown barrier (operations.cc RunLoopOnce exits
        only when EVERY rank requested shutdown): keep the lockstep
        alive with empty submissions + the sd flag until the
        coordinator announces shutdown_done. Rounds keep advancing at
        the cycle pace of still-working ranks, so a finished rank keeps
        serving (rank 0's coordinator included) instead of starving
        peers that still have process-set-scoped work. Rounds use the
        normal response timeout — a peer mid-long-compile is slow, not
        dead, and ending the drain early would starve it (a genuinely
        crashed peer costs one response timeout here, the same as in
        any other stalled round)."""
        if self.broken:
            return
        try:
            while True:
                resp = self.negotiate({}, shutting_down=True)
                if resp.get("shutdown_done"):
                    return
        except Exception:
            return  # peer gone or round timed out: nothing left to serve

    def submit_params(self, params: dict):
        """Rank 0 only: hand tuned knobs to the coordinator; they ride the
        next response and apply on every rank via ``on_params``."""
        if self._coord is not None:
            self._coord.set_params(params)
        elif self.on_params is not None:
            self.on_params(params)

    def stop(self):
        if self._coord:
            self._coord.stop()


class _Coordinator(threading.Thread):
    """Rank-0 aggregation loop (the MessageTable owner, controller.h:35).

    Stall attribution (reference stall_inspector.h:39 + the gathered
    ready-lists of mpi_controller.cc:108): the coordinator knows, per
    pending tensor, exactly which ranks have submitted it — so when a round
    stalls it names the tensors *and the ranks the round is waiting on*,
    and, past ``stall_shutdown_s``, error-closes the round so workers fail
    fast into elastic recovery instead of hanging forever.
    """

    def __init__(self, client, size: int, stall_warning_s: float = 60.0,
                 stall_shutdown_s: float = 0.0):
        super().__init__(daemon=True, name="hvd-coordinator")
        self.client = client
        self.size = size
        self.stall_warning_s = stall_warning_s
        self.stall_shutdown_s = stall_shutdown_s
        self._stop_evt = threading.Event()
        # name -> (sig, set of ranks that submitted) — persists across
        # rounds like the reference's message_table_
        self.table: dict[str, tuple[list, set[int]]] = {}
        self.order: list[str] = []  # rank-0-submission-order tie break
        self.errors: dict[str, str] = {}
        self._pending_params = None  # guarded-by: _params_lock
        self._params_lock = lockcheck.make_lock("controller.params")
        self._down: set[int] = set()
        # source key ("3" = flat rank, "g1" = leader aggregate) -> cached
        # contribution for SAME_AS_LAST fast-path decode, in the unified
        # shape of _decode_contribution (sans the per-round "t" map)
        self._last_submission: dict[str, dict] = {}
        # wire v2: flipped after the round-0 handshake confirms every
        # rank advertised it; the encoder interns across rounds
        self._wire_v2 = False
        self._resp_enc: Optional[wire_mod.ResponseEncoder] = None
        self._m_fanin = None  # hvd_negotiation_fanin, lazy (zero-cost off)
        # adaptive bulk-read target: how many distinct sources closed the
        # last round (size when flat, ~size/k under hierarchy)
        self._expected_sources = size
        # megaplan replay lease (ops/megaplan.py): consecutive rounds in
        # which EVERY source rode the SAME_AS_LAST marker and nothing
        # perturbed the round (errors/join/params/wire upgrade). At the
        # stability threshold the response grants "mp" — all ranks enter
        # and exit replay at the same round boundary. 0 disables the
        # grant entirely (HOROVOD_MEGAPLAN unset).
        self._mp_rounds = 0
        if env_schema.get_bool(env_schema.HOROVOD_MEGAPLAN):
            self._mp_rounds = max(1, env_schema.get_int(
                env_schema.HOROVOD_MEGAPLAN_STABLE_ROUNDS,
                megaplan_mod.DEFAULT_STABLE_ROUNDS))
        self._mp_stable = 0
        # join tracking (reference JoinOp: joined_size / joined ranks,
        # global_state.h:107-111)
        self._joined: set[int] = set()
        self._last_joined_rank: int = -1
        # name -> first time it entered the table (stall attribution)
        self._first_seen: dict[str, float] = {}
        self._stall_warned: set[str] = set()
        # tracing: per-tensor, per-rank first clock-aligned submit times;
        # straggler metrics are created lazily on first attribution so an
        # untraced run exposes no hvd_straggler_* series at all
        self._arrivals: dict[str, dict[int, float]] = {}
        self._m_strag_wait = None
        self._m_strag_last: dict[int, object] = {}
        self.stall_warnings = 0  # observability for tests
        reg = metrics_mod.get_registry()
        self._m_responses = reg.counter(
            "hvd_coordinator_responses_total",
            "negotiation responses published by the rank-0 coordinator")
        self._m_ready = reg.counter(
            "hvd_coordinator_ready_tensors_total",
            "tensors released as globally ready")
        self._m_errors = reg.counter(
            "hvd_coordinator_error_tensors_total",
            "tensors failed with per-tensor errors (mismatch/stall)")
        self._m_stall_warn = reg.counter(
            "hvd_coordinator_stall_warnings_total",
            "coordinator stall warnings (round or per-tensor)")
        # gather-in-progress view for diagnostic bundles: reassigned as a
        # fresh dict each poll (atomic reference swap — the diag probe
        # reads it lock-free from the watchdog thread). THE attribution
        # signal for GET /debug: the ranks the coordinator is waiting on
        # are the wedge by definition (diag.merge_bundles).
        self._gather_state: dict = {}
        diag_mod.register_probe("coordinator", self._diag_probe)

    def _diag_probe(self) -> dict:
        return dict(self._gather_state)

    # Per-attempt poll while gathering a round. Short so a stalled round is
    # noticed and attributed within ~stall_warning_s, not after a silent
    # multi-minute block (the round-1 weakness: the coordinator waited
    # forever without saying which rank was missing).
    POLL_TIMEOUT_S = 1.0

    def set_params(self, params: dict):
        with self._params_lock:
            self._pending_params = params

    def _warn_stall(self, round_no: int, missing: set[int], elapsed: float):
        waiting = {
            n: sorted(set(range(self.size)) - ranks)
            for n, (_, ranks) in self.table.items()
            if len(ranks) < self.size
        }
        detail = "; ".join(
            f"tensor {n!r} waiting on ranks {w}" for n, w in waiting.items()
        ) or "no named tensors pending"
        LOG.warning(
            "Negotiation round %d stalled for %.0f s: ranks %s have not "
            "reported. %s (reference CheckForStalledTensors, "
            "stall_inspector.h:39)",
            round_no, elapsed, sorted(missing), detail)
        self.stall_warnings += 1
        self._m_stall_warn.inc()

    def _error_close_round(self, r: int, missing: set[int], elapsed: float):
        """Past stall_shutdown_s: fail every pending tensor with a message
        naming the absent ranks (reference stall-shutdown,
        stall_inspector.cc + HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)."""
        msg = (f"collective negotiation stalled for {elapsed:.0f} s waiting "
               f"on ranks {sorted(missing)}; shutting the round down "
               "(HOROVOD_STALL_SHUTDOWN_TIME_SECONDS exceeded)")
        errors = {n: msg for n in self.order}
        self.table.clear()
        self.order.clear()
        self.errors.clear()
        # the round's submissions were discarded (some never read), so the
        # SAME_AS_LAST decode cache is stale on both sides: drop it here
        # and tell workers to resend full payloads next round
        self._last_submission.clear()
        self._arrivals.clear()
        self._mp_stable = 0  # error-closed round: replay stability over
        self.client.put(_ctl_scope(r), "resp",
                        json.dumps({"ready": [], "errors": errors,
                                    "invalidate": True}).encode())

    def _decode_contribution(self, source: str, raw: bytes) -> dict:
        """Decode one submission source into the unified contribution
        shape ``{"entries": [(name, sig, ranks)], "covered": set,
        "j": set, "sd": set, "wv": int, "t": {rank: time}}`` —
        format-sniffed per frame (marker / v2 binary / v1 JSON), so a
        flat-fallback rank and a leader aggregate coexist in one round.
        Caches the decoded contribution (sans "t") for markers."""
        if raw[:1] == KVController.SAME_AS_LAST:
            base = self._last_submission.get(source)
            if base is None:
                # marker with nothing cached: same default as v1 — an
                # empty submission that still covers a flat rank (a group
                # marker can claim nothing)
                base = {"entries": [], "j": set(), "sd": set(), "wv": 1,
                        "covered": (set() if source[:1] == "g"
                                    else {int(source)})}
            t_map: dict = {}
            if len(raw) > 1:
                # tracing: marker + {"t": ...} suffix — a float for a
                # flat rank, {rank: float} for an aggregate
                try:
                    t = json.loads(raw[1:])["t"]
                    if isinstance(t, dict):
                        t_map = {int(k): float(v) for k, v in t.items()}
                    else:
                        t_map = {int(source): float(t)}
                except (ValueError, TypeError, KeyError):
                    t_map = {}
            # mk: this source rode the marker fast path this round — the
            # megaplan stability signal counts all-marker rounds
            return dict(base, t=t_map, mk=True)
        if raw[:1] == _MAGIC_BYTE:
            if wire_mod.is_aggregate(raw):
                m = wire_mod.decode_aggregate(raw)
                contrib = {"entries": [(n, sig, set(ranks))
                                       for n, sig, ranks in m["e"]],
                           "covered": set(m["covered"]),
                           "j": set(m["j"]), "sd": set(m["sd"]),
                           "wv": wire_mod.WIRE_V2}
                t_map = {int(k): float(v)
                         for k, v in (m.get("t") or {}).items()}
            else:
                m = wire_mod.decode_submission(raw)
                k = int(source)
                t = m.pop("t", None)
                contrib = {"entries": [(n, sig, {k}) for n, sig in m["e"]],
                           "covered": {k},
                           "j": {k} if m.get("j") else set(),
                           "sd": {k} if m.get("sd") else set(),
                           "wv": wire_mod.WIRE_V2}
                t_map = {} if t is None else {k: float(t)}
        else:
            msg = json.loads(raw)
            if isinstance(msg, list):  # tolerate bare entry lists
                msg = {"e": msg, "j": False}
            k = int(source)
            t = msg.pop("t", None)  # per-round, not part of the
            t_map = {}              # cached submission set
            if t is not None:
                try:
                    t_map = {k: float(t)}
                except (TypeError, ValueError):
                    t_map = {}
            contrib = {"entries": [(n, sig, {k})
                                   for n, sig in msg.get("e", [])],
                       "covered": {k},
                       "j": {k} if msg.get("j") else set(),
                       "sd": {k} if msg.get("sd") else set(),
                       "wv": int(msg.get("wv") or 1)}
        self._last_submission[source] = contrib
        return dict(contrib, t=t_map, mk=False)

    def _gather_round(self, r: int) -> Optional[list]:
        """Collect submissions until every rank is covered (a flat source
        covers one rank, an aggregate its bitmap), attributing stalls to
        the genuinely missing ranks. Returns the decoded contributions as
        an ordered ``[(source, contribution)]`` list, or None when
        stopping or after an error-close."""
        import time as _time

        got: dict[str, dict] = {}
        covered: set[int] = set()
        world = set(range(self.size))
        start = _time.monotonic()
        warned_at = 0.0
        # the bulk-read target adapts to the fan-in: all `size` flat
        # sources in v1, ~size/k aggregates under hierarchy (learned from
        # the previous round — one mis-sized poll converges it)
        min_count = max(1, min(self._expected_sources, self.size))
        # The store's blocking prefix-read wakes the moment min_count
        # submissions exist, so a short first slice costs nothing on the
        # fast path — but when min_count OVERestimates the fan-in (the
        # one round where the world switches from flat sources to
        # aggregates, shrinking sources k-fold) it bounds the stall to
        # ~50ms instead of a full poll interval. The slice ramps back up
        # so genuine straggler waits don't busy-rescan.
        poll_s = 0.05
        while covered != world and not self._stop_evt.is_set():
            # One bulk read per poll: the store blocks until min_count
            # submissions exist (or the poll slice passes and partial
            # results return for stall attribution). Role of the
            # reference's single MPI_Gatherv fan-in
            # (mpi_controller.cc:108) — N sequential GETs per round made
            # the coordinator O(size) HTTP round-trips per cycle.
            bulk = getattr(self.client, "get_prefix", None)
            if bulk is not None:
                try:
                    raw_map = bulk(_ctl_scope(r), "ready/",
                                   min_count=min_count,
                                   timeout=poll_s)
                except Exception:
                    bulk = None  # store without prefix-read support
                    raw_map = {}
                for suffix, raw in raw_map.items():
                    if suffix in got or _source_order(suffix) is None:
                        continue
                    contrib = self._decode_contribution(suffix, raw)
                    got[suffix] = contrib
                    covered |= contrib["covered"]
            if bulk is None:
                for k in sorted(world - covered):
                    try:
                        raw = self.client.get(
                            _ctl_scope(r), f"ready/{k}",
                            timeout=self.POLL_TIMEOUT_S)
                    except Exception:
                        continue  # straggler: keep polling this rank
                    contrib = self._decode_contribution(str(k), raw)
                    got[str(k)] = contrib
                    covered |= contrib["covered"]
            missing = world - covered
            elapsed = _time.monotonic() - start
            self._gather_state = {"round": r,
                                  "missing_ranks": sorted(missing),
                                  "elapsed_s": round(elapsed, 3)}
            if missing and elapsed - warned_at > self.stall_warning_s:
                self._warn_stall(r, missing, elapsed)
                warned_at = elapsed
            if (missing and self.stall_shutdown_s > 0
                    and elapsed > self.stall_shutdown_s):
                self._error_close_round(r, missing, elapsed)
                self._gather_state = {}
                return None
            min_count = min(self.size, len(got) + 1)
            poll_s = min(self.POLL_TIMEOUT_S, poll_s * 4)
        self._gather_state = {}
        if covered != world:
            return None
        self._expected_sources = max(1, len(got))
        return sorted(got.items(), key=lambda kv: _source_order(kv[0]))

    def run(self):
        try:
            # GC every dead generation's rounds (crashed incarnations and
            # pre-reinit lockstep leftovers accumulate in the launcher's
            # store otherwise); the exclusion keeps fresh keys that fast
            # workers of THIS generation may already have published
            self.client.delete_prefix("ctl/", exclude=_ctl_prefix() + "/")
        except Exception:
            pass  # older store without DELETE prefix support
        r = 0
        resp_published = False
        while not self._stop_evt.is_set():
            try:
                resp_published = False
                contribs = self._gather_round(r)
                if contribs is None:
                    if self._stop_evt.is_set():
                        return
                    r += 1  # error-closed round: lockstep advances
                    continue
                for source, contrib in contribs:
                    t_map = contrib.get("t") or {}
                    for k in sorted(contrib["j"]):
                        if k not in self._joined:
                            self._joined.add(k)
                            self._last_joined_rank = k
                    self._down |= contrib["sd"]
                    for name, sig, ranks in contrib["entries"]:
                        for k in sorted(ranks):
                            self._increment(name, sig, k, t_map.get(k))
                self._check_stalled_tensors()
                # A tensor is ready when every rank either submitted it or
                # has joined (joined ranks are implicit zero contributors,
                # reference JoinOp semantics). At least one real submission
                # is required — join alone must not fire ghost collectives.
                ready = [n for n in self.order
                         if not (self._required(n)
                                 - self.table[n][1] - self._joined)]
                join_done = None
                if len(self._joined) == self.size:
                    join_done = self._last_joined_rank
                    self._joined.clear()
                    self._last_joined_rank = -1
                    for c in self._last_submission.values():
                        c["j"] = set()
                errors = {n: self.errors[n] for n in list(self.errors)}
                sigs = {n: self.table[n][0] for n in ready}
                strag = self._attribute_stragglers(ready)
                for n in ready:
                    del self.table[n]
                    self.order.remove(n)
                    self._first_seen.pop(n, None)
                    self._stall_warned.discard(n)
                for n in errors:
                    self.table.pop(n, None)
                    if n in self.order:
                        self.order.remove(n)
                    self.errors.pop(n, None)
                    self._first_seen.pop(n, None)
                    self._stall_warned.discard(n)
                    self._arrivals.pop(n, None)
                resp_dict = {"ready": ready, "sigs": sigs,
                             "errors": errors, "join_done": join_done}
                if strag:
                    resp_dict["strag"] = strag
                if len(self._down) == self.size:
                    # reference: shutdown only when every rank requested
                    # it (operations.cc:728 horovod_shutdown semantics)
                    resp_dict["shutdown_done"] = True
                with self._params_lock:
                    if self._pending_params is not None:
                        resp_dict["params"] = self._pending_params
                        self._pending_params = None
                if (r == 0 and not self._wire_v2 and contribs
                        and all(c.get("wv", 1) >= wire_mod.WIRE_V2
                                for _, c in contribs)):
                    # every rank advertised the binary wire in round 0:
                    # confirm in the (still-JSON) response and switch —
                    # any rank without "wv" keeps the whole world on v1
                    resp_dict["wv"] = wire_mod.WIRE_V2
                if self._mp_rounds:
                    # megaplan stability: an all-marker, unperturbed round
                    # extends the streak; anything else (a full payload
                    # from any rank, an error, a join in flight, a params
                    # push, the wire handshake, v2 hierarchy) resets it —
                    # so a lease is only ever granted while every rank is
                    # demonstrably repeating the identical step. Not under
                    # wire v2: leaders merge members every round, so there
                    # is no per-rank marker signal to count.
                    stable = (not errors and join_done is None
                              and not self._joined and not self._down
                              and not self._wire_v2
                              and "wv" not in resp_dict
                              and "params" not in resp_dict
                              and all(c.get("mk") for _, c in contribs))
                    self._mp_stable = self._mp_stable + 1 if stable else 0
                    if self._mp_stable >= self._mp_rounds:
                        resp_dict["mp"] = True
                if self._resp_enc is not None:
                    raw_resp = self._resp_enc.encode(resp_dict)
                else:
                    raw_resp = json.dumps(resp_dict).encode()
                self.client.put(_ctl_scope(r), "resp", raw_resp)
                resp_published = True
                if resp_dict.get("wv"):
                    self._wire_v2 = True
                    self._resp_enc = wire_mod.ResponseEncoder()
                self._m_responses.inc()
                self._m_ready.inc(len(ready))
                self._m_errors.inc(len(errors))
                if self._wire_v2:
                    if self._m_fanin is None:
                        self._m_fanin = metrics_mod.get_registry().gauge(
                            "hvd_negotiation_fanin",
                            "submission sources the coordinator merged in "
                            "the last negotiation round")
                    self._m_fanin.set(len(contribs))
                if r >= 2:
                    if self._wire_v2:
                        # group sub-scopes hash to their own KV shards: a
                        # prefix delete (broadcast when sharded) sweeps
                        # them; delete_scope would only reach one shard
                        self.client.delete_prefix(_ctl_scope(r - 2) + "/")
                    else:
                        self.client.delete_scope(_ctl_scope(r - 2))
                if resp_dict.get("shutdown_done"):
                    return  # all ranks drained: the lockstep is over
                r += 1
            except Exception as e:
                if self._stop_evt.is_set():
                    return
                LOG.warning("coordinator round %d error: %s", r, e)
                self._abort_close(r + 1 if resp_published else r, e)
                return

    def _abort_close(self, r: int, exc: Exception):
        """Fail-fast on coordinator death (reference operations.cc:587 —
        an aborting background loop fails every pending entry instead of
        leaving workers to time out). Publish an abort response for the
        round workers are (or will next be) blocked on: round r if its
        response was not yet published, else round r+1."""
        msg = (f"coordinator aborted in negotiation round: {exc!r}; "
               "pending collectives failed (re-initialize horovod_tpu)")
        errors = {n: msg for n in self.order}
        payload = json.dumps({"ready": [], "errors": errors,
                              "abort": msg, "invalidate": True}).encode()
        try:
            self.client.put(_ctl_scope(r), "resp", payload)
        except Exception:
            pass  # store unreachable: workers fall back to their timeout

    def _required(self, name: str) -> set:
        """Cross-ranks that must submit ``name``: the process set's
        members when the signature carries them (sub-sets), else the
        world (reference: per-ProcessSet message tables)."""
        sig = self.table[name][0]
        if len(sig) > 9 and sig[9]:
            return set(sig[9])
        return set(range(self.size))

    def _check_stalled_tensors(self):
        """Per-tensor stall attribution after a completed round: a tensor
        submitted by some ranks but not others for longer than
        ``stall_warning_s`` gets a warning naming the absent ranks; past
        ``stall_shutdown_s`` it is error-closed so the submitting ranks
        fail fast (reference CheckForStalledTensors, stall_inspector.h:39,
        and InvalidateStalledCachedTensors)."""
        import time as _time

        now = _time.monotonic()
        for n, (_, ranks) in list(self.table.items()):
            required = self._required(n)
            if not (required - ranks - self._joined) or n in self.errors:
                continue
            age = now - self._first_seen.get(n, now)
            missing = sorted(required - ranks - self._joined)
            if (self.stall_shutdown_s > 0 and age > self.stall_shutdown_s):
                self.errors[n] = (
                    f"tensor {n!r} stalled for {age:.0f} s waiting on ranks "
                    f"{missing}; exceeded "
                    "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS")
            elif age > self.stall_warning_s and n not in self._stall_warned:
                LOG.warning(
                    "Tensor %r has been ready on ranks %s for %.0f s but is "
                    "still waiting on ranks %s. One or more processes may "
                    "have stopped submitting this collective.",
                    n, sorted(ranks), age, missing)
                self._stall_warned.add(n)
                self.stall_warnings += 1
                self._m_stall_warn.inc()

    def _increment(self, name: str, sig: list, rank: int,
                   t_sub: Optional[float] = None):
        """IncrementTensorCount + mismatch validation (controller.cc:942,
        :471-748). ``t_sub`` is the submitting rank's clock-aligned submit
        time (tracing on): the *first* one per (tensor, rank) is kept —
        re-submissions across rounds are the same pending op, and the
        coordinator's own gather blocks until every rank reported, so
        worker-reported times are the only per-rank arrival signal with
        sub-round resolution."""
        import time as _time

        if t_sub is not None:
            self._arrivals.setdefault(name, {}).setdefault(rank, t_sub)
        if name not in self.table:
            self.table[name] = (sig, {rank})
            self.order.append(name)
            self._first_seen[name] = _time.monotonic()
            return
        ref_sig, ranks = self.table[name]
        if sig != ref_sig:
            self.errors[name] = (
                f"Mismatched submissions for tensor {name!r}: rank {rank} "
                f"sent {sig}, previously {ref_sig} (reference "
                "controller.cc:538-619 semantics)")
            return
        ranks.add(rank)

    def _attribute_stragglers(self, ready: list[str]) -> dict:
        """Per released tensor: which rank's submit was last and how long
        the fastest submitter waited (critical-path attribution). Only
        when every required rank reported a submit time — a partial set
        would misattribute. Feeds hvd_straggler_* metrics and rides the
        response so every rank stamps its spans identically."""
        strag: dict[str, list] = {}
        for n in ready:
            arr = self._arrivals.pop(n, None)
            if not arr or len(arr) < 2:
                continue
            required = self._required(n) - self._joined
            if not required.issubset(arr.keys()):
                continue
            times = {k: arr[k] for k in required}
            last = max(times, key=lambda k: times[k])
            wait = max(times.values()) - min(times.values())
            strag[n] = [last, round(wait, 6)]
            if self._m_strag_wait is None:
                reg = metrics_mod.get_registry()
                self._m_strag_wait = reg.histogram(
                    "hvd_straggler_wait_seconds",
                    "per-collective wait between the fastest and the "
                    "last-submitting rank (clock-aligned)",
                    buckets=tracing_mod.STRAGGLER_BUCKETS_S)
            self._m_strag_wait.observe(wait)
            c = self._m_strag_last.get(last)
            if c is None:
                c = self._m_strag_last[last] = \
                    metrics_mod.get_registry().counter(
                        "hvd_straggler_last_rank_total",
                        "collectives for which this rank submitted last",
                        rank=str(last))
            c.inc()
        return strag

    def stop(self):
        diag_mod.unregister_probe("coordinator")
        self._stop_evt.set()
