"""Async named-tensor runtime: tensor queue, cycle loop, fusion, handles.

This is the TPU-shaped survivor of the reference's background machinery:

- `TensorQueue`  — mutex-protected pending table bridging caller threads to
  the cycle thread (reference tensor_queue.{h,cc}; duplicate-name guard
  common.h:169).
- `HandleManager` — int handles for async ops with poll/wait semantics
  (reference torch/handle_manager.{h,cc}, mpi_ops_v2.cc:474-516).
- `BackgroundRuntime` — the cycle loop (reference BackgroundThreadLoop /
  RunLoopOnce, operations.cc:353/587): every ``cycle_time_ms`` it drains the
  queue, *fuses* same-(op,dtype) tensors into one flat buffer up to
  ``fusion_threshold_bytes`` (reference fusion_buffer_manager.h + the
  FuseResponses look-ahead, controller.cc:777-849), and dispatches one
  compiled collective per fused group.

Two deliberate departures from the reference, both TPU-native:

1. There is no negotiation round-trip in the common case. JAX dispatch is
   itself asynchronous — the cycle thread *launches* compiled programs and
   returns; device completion is observed per-handle via ``is_ready()``
   (replaces the GPU finalizer thread pool, gpu_operations.h:107).
2. The "response cache" is the compiled-program cache keyed by fused
   signature (`collectives._EAGER_CACHE`): a steady-state training loop hits
   identical signatures every step and skips straight to execution, which is
   exactly the role of response_cache.{h,cc} in the reference.

In multi-process mode, deterministic cross-process ordering is achieved by
sorting each drained batch by tensor name before fusing — all processes that
submitted the same set execute the same fused programs in the same order
(the coordinator's job in reference controller.cc:69). True negotiation for
mismatched sets arrives with the rendezvous-store controller
(horovod_tpu.runner).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..common.exceptions import DuplicateNameError, HorovodInternalError
from ..utils import anatomy as anatomy_mod
from ..utils import diag as diag_mod
from ..utils import faults as faults_mod
from ..utils import flightrec as flightrec_mod
from ..utils import lockcheck
from ..utils import metrics as metrics_mod
from ..utils import perfledger as perfledger_mod
from ..utils import tracing as tracing_mod
from . import collectives as C
from . import compression as compression_mod
from . import megaplan as megaplan_mod

LOG = logging.getLogger("horovod_tpu")


@dataclass
class TensorEntry:
    """One pending op (reference TensorTableEntry, common.h:197-240)."""

    name: str
    op: str  # allreduce | allgather | broadcast | alltoall | reducescatter
    tensor: Any
    reduce_op: C.ReduceOp = C.ReduceOp.AVERAGE
    root_rank: int = 0
    splits: Any = None
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    process_set: Any = None
    # per-call quantized-wire override (compression.QuantSpec) from a
    # Compression.int8/int4 marker; None defers to HOROVOD_COMPRESSION
    quant: Any = None
    handle: int = -1
    enqueue_time: float = field(default_factory=time.monotonic)
    # lifecycle trace span (utils/tracing.py); None unless HOROVOD_TRACE
    span: Any = None


class HandleManager:
    """Handle → status/result table (reference handle_manager.h:31)."""

    def __init__(self):
        self._lock = lockcheck.make_lock("queue.handles")
        self._next = 0  # guarded-by: _lock
        self._results: dict[int, tuple[threading.Event, Any, Optional[BaseException]]] = {}  # guarded-by: _lock

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._results[h] = (threading.Event(), None, None)
            return h

    def mark_done(self, handle: int, result=None, exc: Optional[BaseException] = None):
        with self._lock:
            rec = self._results.get(handle)
            if rec is None:
                return  # already consumed (shutdown race); nothing to signal
            ev = rec[0]
            self._results[handle] = (ev, result, exc)
        ev.set()

    def poll(self, handle: int) -> bool:
        """True once the op was *launched* and its result is materialized on
        device or failed (reference PollHandle, mpi_ops_v2.cc:474)."""
        with self._lock:
            ev, result, exc = self._results[handle]
        if not ev.is_set():
            return False
        if exc is not None:
            return True
        try:
            return bool(result.is_ready()) if hasattr(result, "is_ready") else True
        except Exception:
            return True

    def wait(self, handle: int):
        """Block until complete; raise on failure; pop and return the result
        (reference WaitAndClear, mpi_ops_v2.cc:479)."""
        with self._lock:
            ev, _, _ = self._results[handle]
        ev.wait()
        with self._lock:
            _, result, exc = self._results.pop(handle)
        if exc is not None:
            raise exc
        import jax

        return jax.block_until_ready(result)


class TensorQueue:
    """Pending-op FIFO with in-flight name guard (reference tensor_queue.h)."""

    def __init__(self):
        self._lock = lockcheck.make_lock("queue.pending")
        self._queue: list[TensorEntry] = []  # guarded-by: _lock
        self._in_flight: set[str] = set()  # guarded-by: _lock
        self._finalized = False  # guarded-by: _lock

    def push(self, entry: TensorEntry):
        with self._lock:
            if self._finalized:
                raise HorovodInternalError("runtime is shut down")
            if entry.name in self._in_flight:
                raise DuplicateNameError(
                    f"a tensor named {entry.name!r} is already in flight "
                    "(reference DUPLICATE_NAME_ERROR, common.h:169)")
            self._in_flight.add(entry.name)
            self._queue.append(entry)

    def drain(self) -> list[TensorEntry]:
        with self._lock:
            batch, self._queue = self._queue, []
            return batch

    def release(self, name: str):
        with self._lock:
            self._in_flight.discard(name)

    def finalize(self) -> list[TensorEntry]:
        """Fail-all on shutdown (reference FinalizeTensorQueue,
        tensor_queue.h:35)."""
        with self._lock:
            self._finalized = True
            batch, self._queue = self._queue, []
            self._in_flight.clear()
            return batch


class BackgroundRuntime:
    """The cycle loop (reference RunLoopOnce, operations.cc:587)."""

    def __init__(self, process_set, config, timeline=None, stall_inspector=None):
        self.process_set = process_set
        self.cycle_time_ms = config.cycle_time_ms
        self.fusion_threshold = config.fusion_threshold_bytes
        self.timeline = timeline
        self.stall = stall_inspector
        self.queue = TensorQueue()
        self.handles = HandleManager()
        # fusion pack helper (reference fusion_buffer_manager.h:40);
        # native batched-memcpy when the C++ core is built, staging into a
        # persistent ring sized to the fusion threshold
        from .._native import FusionBuffer

        self.staging_ring_slots = max(
            1, int(getattr(config, "staging_ring_slots", 4)))
        self.fusion_buffer = FusionBuffer(
            config.fusion_threshold_bytes,
            slots=self.staging_ring_slots)
        # fused-plan granularity: max tensors per chunk (0 = byte-bounded
        # only) — the autotuner's chunk knob (HOROVOD_PLAN_CHUNK_TENSORS)
        self.plan_chunk_tensors = max(
            0, int(getattr(config, "plan_chunk_tensors", 0)))
        # compiled fused-chunk plans (collectives.fused_chunk_plan) replay
        # the whole pack→reduce→unpack chain as one program per chunk;
        # HOROVOD_FUSED_PLAN_DISABLE falls back to the per-cycle eager chain
        self._plans_enabled = not getattr(config, "fused_plan_disable", False)
        self._pending: dict[str, TensorEntry] = {}  # negotiated-path backlog
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        # perf counters for the autotuner (reference parameter_manager scoring
        # is bytes/sec, parameter_manager.h:88)
        self.bytes_processed = 0
        self.cycles = 0
        self.work_cycles = 0
        # metric handles resolved ONCE here — the cycle loop and enqueue
        # path only touch pre-built Counter/Gauge/Histogram objects (O(1)
        # int ops under one lock, no label-string allocation per event)
        reg = metrics_mod.get_registry()
        self.metrics = reg
        self._m_cycle = reg.histogram(
            "hvd_cycle_seconds", "working background-cycle duration",
            buckets=metrics_mod.LATENCY_BUCKETS_S)
        self._m_queue_depth = reg.gauge(
            "hvd_queue_depth", "pending entries drained this cycle")
        self._m_fusion_batch = reg.histogram(
            "hvd_fusion_batch_size", "tensors fused per allreduce chunk",
            buckets=metrics_mod.BATCH_BUCKETS)
        self._m_fused_bytes = reg.histogram(
            "hvd_fused_chunk_bytes", "bytes per fused allreduce chunk",
            buckets=metrics_mod.SIZE_BUCKETS_BYTES)
        self._m_cycles_idle = reg.counter(
            "hvd_cycles_total", "background cycles", kind="idle")
        self._m_cycles_work = reg.counter(
            "hvd_cycles_total", "background cycles", kind="work")
        self._m_neg_rounds = reg.counter(
            "hvd_negotiation_rounds_total", "controller negotiation rounds")
        self._m_neg_errors = reg.counter(
            "hvd_negotiation_errors_total",
            "tensors failed by negotiation responses")
        self._m_op_errors = reg.counter(
            "hvd_op_errors_total", "eager ops failed during execution")
        # per-(op, dtype) lazily cached handles: one dict lookup per event
        self._m_by_op: dict[tuple, tuple] = {}
        self._m_enq: dict[str, Any] = {}
        self.autotuner = None  # attached by context.init when HOROVOD_AUTOTUNE
        # resolved here (not getattr'd in the cycle loop) so the autotune
        # hook below stays one is-None check when tuning is off
        self.autotune_steps_per_sample = max(
            1, int(getattr(config, "autotune_steps_per_sample", 20)))
        # join state (reference JoinOp / hvd.join(): a rank out of data keeps
        # participating in other ranks' collectives with zero contributions
        # until everyone has joined)
        self.joined = False
        self._join_done_evt = threading.Event()
        self._join_last_rank = -1
        # cross-rank tracing: resolved once; None keeps every span hook a
        # single ``is not None`` check (the zero-cost contract enforced by
        # benchmarks/trace_overhead.py)
        self.tracer = tracing_mod.get_tracer()
        # postmortem layer, same resolved-once contract
        # (benchmarks/flightrec_overhead.py): None handles keep the cycle
        # loop and negotiation bracket at one is-None check each
        self.recorder = flightrec_mod.get_recorder()
        self.watchdog = diag_mod.get_watchdog()
        # per-step performance ledger, same resolved-once contract
        # (benchmarks/perfledger_overhead.py): a None handle keeps the
        # cycle loop at one is-None check per phase stamp
        self.ledger = perfledger_mod.get_ledger()
        # step-anatomy profiler, same resolved-once contract
        # (benchmarks/anatomy_overhead.py): a None handle keeps every
        # dispatch hook at one is-None check
        self.profiler = anatomy_mod.get_profiler()
        # whole-step megaplan capture & replay (ops/megaplan.py), same
        # resolved-once contract (benchmarks/megaplan_overhead.py): a
        # None handle keeps run_cycle at one is-None check per cycle
        self._mp = megaplan_mod.get_manager()
        # chunk schedule being recorded this cycle (cycle-thread-only
        # scratch): a list while a capture is in progress, None otherwise
        self._mp_capture: Optional[list] = None
        from .._native import chain_dispatch

        self._chain_dispatch = chain_dispatch
        # per-cycle scratch the ledger hooks accumulate into (cycle
        # thread only): execute-window seconds and the round's worst
        # coordinator straggler verdict
        self._perf_exec_s = 0.0
        self._perf_strag: Optional[tuple] = None
        # blockwise quantized wire (ops/compression.py): resolved ONCE.
        # None keeps every quant hook below at a single is-None/or check —
        # the zero-cost contract (tests/test_quantized.py asserts no
        # hvd_quant_* series exist when HOROVOD_COMPRESSION is unset).
        self._quant = compression_mod.resolve_quant_spec(config)
        # ZeRO-1 mutual exclusion (docs/sharded_optimizer.md): with the
        # sharded update on, the compression knob must stay "none" — the
        # autotuner's validation path rejects proposals that violate it
        self._sharded_update = bool(getattr(config, "sharded_update", False))
        # residual store / opt-out registry materialize lazily on the
        # first quantized group (a per-call Compression.int8 marker can
        # arrive with the env knob unset)
        self._quant_residuals = None
        self._quant_optout = None
        self._quant_min_elems = 0
        self._quant_noted: set = set()
        self.controller = self._maybe_controller()
        if self.controller is not None:
            self.controller.on_params = self._apply_tuned_params
        if self.controller is not None and self.stall is not None:
            # multi-process: the coordinator owns stall *shutdown* (it can
            # attribute the missing ranks — reference stall_inspector runs
            # coordinator-side); the local inspector keeps the warning role
            self.stall.shutdown_time_s = 0.0

    def _validate_tuned_params(self, p: dict) -> dict:
        """Parse/validate a tuned-params dict into typed knob values,
        raising BEFORE anything is applied — the all-or-nothing contract:
        a torn or malformed proposal must never leave the runtime with
        half a config (docs/autotune.md)."""
        out = {}
        if "fusion" in p:
            v = int(p["fusion"])
            if v <= 0:
                raise ValueError(f"fusion threshold must be > 0, got {v}")
            out["fusion"] = v
        if "cycle" in p:
            v = float(p["cycle"])
            if not v > 0:
                raise ValueError(f"cycle time must be > 0, got {v}")
            out["cycle"] = v
        if "ring_slots" in p:
            v = int(p["ring_slots"])
            if v < 1:
                raise ValueError(f"ring slots must be >= 1, got {v}")
            out["ring_slots"] = v
        if "chunk" in p:
            v = int(p["chunk"])
            if v < 0:
                raise ValueError(f"plan chunk tensors must be >= 0, got {v}")
            out["chunk"] = v
        if "compression" in p:
            mode = str(p["compression"]).strip().lower() or "none"
            # raises for anything outside the closed mode set
            spec = compression_mod.spec_for_mode(mode)
            if spec is not None and self._sharded_update:
                raise ValueError(
                    "compression is mutually exclusive with the sharded "
                    "update (HOROVOD_SHARDED_UPDATE)")
            out["compression"] = spec
        if "hier_group" in p:
            v = int(p["hier_group"])
            if v < 1:
                raise ValueError(f"hier group size must be >= 1, got {v}")
            out["hier_group"] = v
        for k in ("hier_ar", "hier_ag"):
            if k in p:
                out[k] = bool(p[k])
        return out

    def _apply_tuned_params(self, p: dict):
        """Apply coordinator-synchronized tuning knobs (reference
        SynchronizeParameters): called from negotiate() at response
        receipt, so every rank switches knobs at the same round boundary
        relative to the collectives it executes. Validation is
        all-or-nothing (nothing applies if any value is bad); every
        boundary-moving knob routes through its setter, which invalidates
        the affected cached state (plans / staging ring / hier channels)."""
        try:
            knobs = self._validate_tuned_params(p)
            if knobs:
                # one funnel for ALL tuned knobs (the autotuner
                # handshake): a knob landing mid-replay must never let a
                # stale whole-step schedule execute, even for knobs that
                # do not move chunk boundaries — the epoch bump makes
                # the replaying cycle thread miss its next validity
                # check (the individual setters below additionally
                # invalidate through invalidate_fused_plans)
                megaplan_mod.invalidate_megaplan("tuned_params")
            if "fusion" in knobs:
                self.set_fusion_threshold(knobs["fusion"])
            if "cycle" in knobs:
                self.cycle_time_ms = knobs["cycle"]
            if "ring_slots" in knobs:
                self.set_staging_slots(knobs["ring_slots"])
            if "chunk" in knobs:
                self.set_plan_chunk_tensors(knobs["chunk"])
            if "compression" in knobs:
                self.set_compression_spec(knobs["compression"])
            if "hier_group" in knobs and self.controller is not None:
                self.controller.set_group_size(knobs["hier_group"])
            if "hier_ar" in knobs or "hier_ag" in knobs:
                from ..common import context as ctx_mod

                cfg = ctx_mod.context().config
                cfg.hierarchical_allreduce = bool(
                    knobs.get("hier_ar", cfg.hierarchical_allreduce))
                cfg.hierarchical_allgather = bool(
                    knobs.get("hier_ag", cfg.hierarchical_allgather))
                if "hier_group" in knobs:
                    cfg.hier_group_size = knobs["hier_group"]
        finally:
            at = self.autotuner
            if at is not None and p.get("final"):
                at.done = True

    def set_staging_slots(self, slots: int):
        """Adopt a new staging-ring depth (autotuner ring knob); a no-op
        when unchanged — the ring rebuild drops idle buffers while
        in-flight leases keep their own references."""
        slots = max(1, int(slots))
        if slots == self.staging_ring_slots:
            return
        self.staging_ring_slots = slots
        try:
            self.fusion_buffer.set_slots(slots)
        except Exception:
            LOG.exception("staging ring slot resize failed")
        # a captured megaplan chains dispatches through the ring; a
        # depth change mid-replay re-captures under the new topology
        megaplan_mod.invalidate_megaplan("ring_slots")

    def set_plan_chunk_tensors(self, n: int):
        """Adopt a new per-chunk tensor cap. Chunk boundaries move, so
        cached fused-chunk plans are invalidated like a fusion-threshold
        change — stale signatures would crowd live programs out of the
        shared LRU."""
        n = max(0, int(n))
        if n == self.plan_chunk_tensors:
            return
        self.plan_chunk_tensors = n
        C.invalidate_fused_plans()

    def set_compression_spec(self, spec):
        """Adopt a new runtime wire spec (None / cast / blockwise —
        compression.spec_for_mode). Plans carry the quant signature in
        their keys, but the old flavor's programs are dead weight in the
        LRU, so the cache is dropped; the per-name fallback note set
        resets so the new mode re-explains its fallbacks."""
        if spec == self._quant:
            return
        self._quant = spec
        self._quant_noted.clear()
        C.invalidate_fused_plans()

    def set_fusion_threshold(self, nbytes: int):
        """Adopt a new fusion threshold. Chunk boundaries move, so the
        staging ring is resized and every cached fused-chunk plan is
        invalidated — their signatures can never be looked up again and
        would otherwise crowd live programs out of the shared LRU."""
        nbytes = int(nbytes)
        if nbytes == self.fusion_threshold:
            return
        self.fusion_threshold = nbytes
        try:
            self.fusion_buffer.resize(nbytes)
        except Exception:
            LOG.exception("staging ring resize failed")
        C.invalidate_fused_plans()

    def _maybe_controller(self):
        """Cross-process negotiation over the launcher's rendezvous store —
        only when there is real multi-process dynamism to coordinate."""
        import os

        from ..common import env as env_schema

        if self.process_set.cross_size <= 1:
            return None
        addr = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_ADDR)
        port = os.environ.get(env_schema.HOROVOD_GLOO_RENDEZVOUS_PORT)
        if not addr or not port:
            LOG.warning(
                "multi-process run without a rendezvous store: eager async "
                "ops fall back to name-ordered execution (launch with hvdrun "
                "for full negotiation)")
            return None
        from ..runner.http_server import KVStoreClient
        from .controller import KVController

        from ..common import context as ctx_mod

        try:
            cfg = ctx_mod.context().config
            warn_s, shut_s = cfg.stall_warning_time_s, cfg.stall_shutdown_time_s
            resp_s = cfg.response_timeout_s
            hier = cfg.hier_negotiation
            hier_k, hier_fb = cfg.hier_group_size, cfg.hier_fallback_s
        except Exception:
            warn_s, shut_s, resp_s = 60.0, 0.0, KVController.RESPONSE_TIMEOUT_S
            hier, hier_k, hier_fb = None, None, None
        return KVController(KVStoreClient(addr, int(port)),
                            rank=self.process_set.cross_rank,
                            size=self.process_set.cross_size,
                            poll_timeout=resp_s,
                            stall_warning_s=warn_s,
                            stall_shutdown_s=shut_s,
                            hier=hier, hier_group_size=hier_k,
                            hier_fallback_s=hier_fb)

    def _op_metrics(self, op: str, dtype: str) -> tuple:
        """(bytes_total, latency_hist, ops_total) for one (op, dtype) —
        created on the first event of that shape, a dict hit afterwards."""
        key = (op, dtype)
        handles = self._m_by_op.get(key)
        if handles is None:
            reg = self.metrics
            handles = (
                reg.counter(f"hvd_{op}_bytes_total",
                            f"bytes processed by eager {op}", dtype=dtype),
                reg.histogram(f"hvd_{op}_latency_seconds",
                              f"eager {op} launch latency",
                              buckets=metrics_mod.LATENCY_BUCKETS_S,
                              dtype=dtype),
                reg.counter(f"hvd_{op}_ops_total",
                            f"eager {op} operations launched", dtype=dtype),
            )
            self._m_by_op[key] = handles
        return handles

    # -- public enqueue API -------------------------------------------------
    def enqueue(self, entry: TensorEntry) -> int:
        entry.handle = self.handles.allocate()
        c = self._m_enq.get(entry.op)
        if c is None:
            c = self._m_enq[entry.op] = self.metrics.counter(
                "hvd_ops_enqueued_total", "eager ops enqueued", op=entry.op)
        c.inc()
        if self.stall:
            self.stall.record_pending(entry.name)
        if self.timeline:
            self.timeline.negotiate_start(entry.name, entry.op.upper())
        if self.tracer is None:
            self.queue.push(entry)
        else:
            entry.span = self.tracer.begin(entry.name, entry.op)
            try:
                self.queue.push(entry)
            except BaseException:
                # rejected entries (duplicate name, shut-down queue) never
                # reach _finish — close the span here or it leaks open
                self.tracer.finish(entry.span, error=True)
                raise
        self._wake.set()
        return entry.handle

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-cycle")
        self._thread.start()
        # live-state probe for diagnostic bundles: only started runtimes
        # register (the overhead benches build private non-started ones)
        diag_mod.register_probe("runtime", self._diag_probe)

    def _diag_probe(self) -> dict:
        state = {
            "cycles": self.cycles,
            "work_cycles": self.work_cycles,
            "pending": len(self._pending),
            "joined": self.joined,
            "controller": self.controller is not None,
        }
        if self.watchdog is not None:
            state["watchdog"] = self.watchdog.state()
        return state

    def stop(self, drain: bool = True):
        diag_mod.unregister_probe("runtime")
        self._stop.set()
        self._wake.set()
        cycle_exited = True
        if self._thread:
            self._thread.join(timeout=10)
            cycle_exited = not self._thread.is_alive()
            self._thread = None
        if self.controller:
            # reference shutdown barrier: keep the lockstep (and rank 0's
            # coordinator) alive until EVERY rank has requested shutdown —
            # a finished rank exiting early would starve peers that still
            # have process-set-scoped rounds to run. Never drain while the
            # cycle thread may still be mid-negotiate (two threads on one
            # controller would corrupt the round lockstep), and not on
            # error-recovery teardown (drain=False).
            if drain and cycle_exited:
                self.controller.drain_shutdown()
            self.controller.stop()
        for e in list(self._pending.values()) + self.queue.finalize():
            if e.span is not None and self.tracer is not None:
                self.tracer.finish(e.span, error=True)
                e.span = None
            self.handles.mark_done(
                e.handle, exc=HorovodInternalError("Horovod has been shut down"))
        self._pending.clear()

    # -- cycle ---------------------------------------------------------------
    def _loop(self):
        while not self._stop.is_set():
            if self.watchdog is not None:
                self.watchdog.beat()
            self._wake.wait(timeout=self.cycle_time_ms / 1000.0)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.run_cycle()
            except Exception:
                LOG.exception("background cycle failed")

    def run_cycle(self):
        self.cycles += 1
        batch = self.queue.drain()
        cycle_t0 = time.perf_counter()
        led = self.ledger
        profiler = self.profiler
        timed = led is not None or profiler is not None
        t_neg = t_disp = 0.0
        if timed:
            self._perf_exec_s = 0.0
            self._perf_strag = None
        if batch:
            self._m_queue_depth.set(len(batch))
            if self.tracer is not None:
                now = time.time()
                for e in batch:
                    if e.span is not None:
                        e.span.t[tracing_mod.T_DRAIN] = now
        # mark only working cycles: an idle 1 kHz loop would flood the trace
        # with meaningless CYCLE_START instants
        if self.timeline and batch:
            self.timeline.mark_cycle_start()
        if self.stall:
            try:
                self.stall.check()
            except Exception as e:
                # Fail exactly the stalled entries and keep the cycle loop
                # alive: a dead loop would stop negotiation rounds and
                # deadlock every healthy rank (reference behavior: stall
                # shutdown aborts the affected tensors/job, the background
                # thread itself keeps servicing its queue until shutdown).
                names = getattr(e, "names", None)
                if names is None:  # unknown failure: fail this batch
                    for entry in batch:
                        self._finish(entry, None, e)
                    raise
                err = HorovodInternalError(str(e))
                remaining = []
                for entry in batch:
                    if entry.name in names:
                        self._finish(entry, None, err)
                    else:
                        remaining.append(entry)
                batch = remaining
                for n in names:
                    entry = self._pending.pop(n, None)
                    if entry is not None:
                        self._finish(entry, None, err)
        # steady-state replay: a live megaplan short-circuits the whole
        # negotiated path to ~one validity check + one chained dispatch
        # (docs/performance.md "Whole-step replay"); a miss invalidates
        # and falls through to the negotiated path below
        mp = self._mp
        if mp is not None and batch and mp.plan is not None:
            if self._megaplan_cycle(batch, cycle_t0, timed):
                return
        if self.controller is not None:
            _pt = time.perf_counter() if timed else 0.0
            batch = self._negotiate(batch)
            if timed:
                t_neg = time.perf_counter() - _pt
        elif self.process_set.cross_size > 1 and batch:
            # no rendezvous store: best-effort deterministic order
            batch.sort(key=lambda e: e.name)
        if not batch:
            # idle cycles (nothing executed, post-negotiation) tick a
            # counter only — timing a 1 kHz idle loop would drown the
            # histogram the same way CYCLE_START instants would flood
            # the trace
            self._m_cycles_idle.inc()
            return
        self._m_cycles_work.inc()
        # megaplan stability: count consecutive identical batch
        # signatures on negotiated working cycles; at the stability
        # threshold, THIS cycle's dispatch records the chunk schedule
        # (the capture list filled by _run_fused_allreduce) — only when
        # the whole step is plan-replayable and, multi-process, the
        # coordinator granted the replay lease at the same boundary
        cap_sig = None
        if mp is not None:
            cap_sig = megaplan_mod.batch_signature(batch)
            if (mp.observe(cap_sig) and self._plans_enabled
                    and not self._pending and not self.joined
                    and (self.controller is None
                         or self.controller.megaplan_lease)):
                self._mp_capture = []
        t_disp = self._dispatch_batch(batch, timed)
        if self._mp_capture is not None:
            self._megaplan_commit(cap_sig, batch)
        self._finish_cycle(batch, cycle_t0, timed, t_neg, t_disp)

    def _dispatch_batch(self, batch: list[TensorEntry], timed: bool) -> float:
        """Group a ready batch into fusable chunks vs singletons and
        dispatch them; returns the dispatch-window seconds (0.0 when
        untimed). Shared by the negotiated path and the megaplan
        lease-drop fallback."""
        # split into fusable allreduce groups vs singletons
        fusable: dict[tuple, list[TensorEntry]] = {}
        singles: list[TensorEntry] = []
        for e in batch:
            if e.op == "allreduce" and e.reduce_op in (C.ReduceOp.SUM, C.ReduceOp.AVERAGE):
                # metadata only — np.asarray here would pull a
                # device-resident jax.Array to host just to read its dtype
                t = e.tensor
                dtype = str(getattr(t, "dtype", None)
                            or np.asarray(t).dtype)
                # key on the stable process-set NAME, not id(): id() of a
                # GC-reclaimed dead set can be recycled for a new one and
                # alias two different sets into one fused group. The name
                # is registry-unique, and None (default set) folds into
                # the runtime set it resolves to at dispatch.
                ps = e.process_set or self.process_set
                key = (dtype, int(e.reduce_op), e.prescale_factor,
                       e.postscale_factor, getattr(ps, "name", "global"),
                       # per-call quant markers must not fuse with
                       # differently-quantized (or unquantized) entries —
                       # the chunk shares one wire format
                       None if e.quant is None else e.quant.signature())
                fusable.setdefault(key, []).append(e)
            else:
                singles.append(e)
        if singles:
            # singletons dispatch eagerly, outside any compiled chunk
            # plan: a step containing one is not whole-step replayable
            self._mp_capture = None
        if timed:
            _pt = time.perf_counter()
        for key, group in fusable.items():
            self._run_fused_allreduce(group)
        for e in singles:
            self._run_single(e)
        return time.perf_counter() - _pt if timed else 0.0

    def _finish_cycle(self, batch: list[TensorEntry], cycle_t0: float,
                      timed: bool, t_neg: float, t_disp: float):
        """Working-cycle epilogue: wall histogram, perf-ledger and
        anatomy step records, autotune hooks. Shared by the negotiated
        path and megaplan replay so attribution stays uniform."""
        wall = time.perf_counter() - cycle_t0
        self._m_cycle.observe(wall)
        led = self.ledger
        if led is not None:
            led.record_step(wall, negotiate_s=t_neg, dispatch_s=t_disp,
                            exec_s=self._perf_exec_s, tensors=len(batch),
                            straggler=self._perf_strag)
        profiler = self.profiler
        if profiler is not None:
            profiler.record_step(wall, negotiate_s=t_neg, dispatch_s=t_disp,
                                 tensors=len(batch),
                                 names=[e.name for e in batch],
                                 straggler=self._perf_strag)
        # autotune hook on working cycles (reference: ParameterManager
        # scores each cycle's bytes/sec, parameter_manager.h:88) — one
        # is-None check when tuning is off (the zero-cost contract gated
        # by benchmarks/autotune_overhead.py); the workload signature
        # feeding shift detection is computed inside the guard
        self.work_cycles += 1
        at = self.autotuner
        if at is not None:
            at.note_cycle(batch)
            if self.work_cycles % self.autotune_steps_per_sample == 0:
                try:
                    at.sample()
                except Exception:
                    LOG.exception("autotune sample failed")

    def _megaplan_commit(self, sig, batch: list[TensorEntry]):
        """Install the chunk schedule recorded during this cycle's
        dispatch as the live megaplan — only when every batch entry rode
        a compiled chunk plan (singles/quant/legacy/failed chunks abort
        the capture list). The ``megaplan.capture`` fault site lets
        chaos tests kill a capture at the commit boundary: an injected
        failure re-arms cleanly, never installs a torn schedule."""
        cap, self._mp_capture = self._mp_capture, None
        mp = self._mp
        if not cap or sum(len(c[0]) for c in cap) != len(batch):
            mp.abort_capture()
            return
        try:
            faults_mod.fault_point("megaplan.capture")
            mp.commit(megaplan_mod.Megaplan(
                sig=sig, chunks=tuple(cap), epoch=megaplan_mod.epoch(),
                plan_epoch=C._plan_epoch()))
        except Exception as exc:
            LOG.warning("megaplan capture aborted: %s", exc)
            mp.abort_capture()

    def _megaplan_cycle(self, batch: list[TensorEntry], cycle_t0: float,
                        timed: bool) -> bool:
        """One steady-state cycle against the captured megaplan.

        Returns True when the cycle was fully handled — replayed, or
        (multi-process) degraded-but-dispatched after a lease drop whose
        round was already consumed. Returns False on a validity miss
        BEFORE any round or dispatch, so the normal negotiated path runs
        this cycle from scratch; every miss invalidates and re-arms.
        """
        mp = self._mp
        plan = mp.plan
        # the ~single is-valid check of the replay fast path: two epoch
        # ints (knob/autotune invalidations + the elastic generation),
        # membership, then the batch signature
        if (plan.epoch != megaplan_mod.epoch()
                or plan.plan_epoch != C._plan_epoch()):
            mp.invalidate("epoch")
            return False
        if self.joined or self._pending:
            mp.invalidate("membership")
            return False
        if megaplan_mod.batch_signature(batch) != plan.sig:
            mp.invalidate("signature")
            return False
        ctl = self.controller
        if ctl is not None and not ctl.megaplan_lease:
            # the coordinator withheld the grant on the previous response
            # (another rank broke stability): negotiate this round fully
            mp.invalidate("lease")
            return False
        try:
            # chaos site: fires BEFORE any ring lease or dispatch, so an
            # injected mid-replay invalidation degrades to negotiated
            # mode with zero leaked spans and no torn ring state
            faults_mod.fault_point("megaplan.replay")
        except Exception as exc:
            LOG.warning("megaplan replay fault: %s", exc)
            mp.invalidate("fault")
            return False
        t_neg = 0.0
        if ctl is not None:
            # replay-mode lease round: the 1-byte SAME_AS_LAST marker
            # keeps the lockstep advancing (and the coordinator's
            # stability count alive) without re-serializing the
            # submission; the full control path (params/abort/shutdown)
            # still applies — see KVController.lease_round
            _pt = time.perf_counter() if timed else 0.0
            if self.watchdog is not None:
                self.watchdog.enter("negotiate")
            try:
                resp = ctl.lease_round()
            except Exception as exc:
                if self._stop.is_set():
                    err: Exception = HorovodInternalError(
                        "Horovod has been shut down")
                else:
                    LOG.error("lease round failed: %s", exc)
                    err = HorovodInternalError(
                        f"controller negotiation failed: {exc}")
                for e in batch:
                    self._finish(e, None, err)
                mp.invalidate("controller")
                return True
            finally:
                if self.watchdog is not None:
                    self.watchdog.exit_phase("negotiate")
            if timed:
                t_neg = time.perf_counter() - _pt
            if (not ctl.megaplan_lease or resp.get("errors")
                    or resp.get("join_done") is not None
                    or plan.epoch != megaplan_mod.epoch()):
                # the lease broke mid-round (another rank's set changed,
                # a params push bumped the epoch, a rank joined): the
                # round IS consumed — our cached submission was merged —
                # so process its response like a negotiated round and
                # dispatch whatever it released; re-negotiating would
                # desync the lockstep
                mp.invalidate("lease")
                for e in batch:
                    self._pending[self._wire_name(e)] = e
                out = self._process_response(resp)
                if not out:
                    self._m_cycles_idle.inc()
                    return True
                self._m_cycles_work.inc()
                t_disp = self._dispatch_batch(out, timed)
                self._finish_cycle(out, cycle_t0, timed, t_neg, t_disp)
                return True
        # replay: one chained dispatch through the staging ring
        # (_native.chain_dispatch) over the captured schedule
        self._m_cycles_work.inc()
        by = {e.name: e for e in batch}
        if self.tracer is not None:
            disp0 = time.time()
            for e in batch:
                if e.span is not None:
                    e.span.t[tracing_mod.T_DISPATCH_START] = disp0
        _dt0 = time.perf_counter()
        steps = []
        for names, cplan, on_dev, nbytes, dtype in plan.chunks:
            entries = [by[n] for n in names]
            if on_dev:
                arrs = [e.tensor for e in entries]
            else:
                arrs = [np.asarray(e.tensor) for e in entries]
            steps.append((cplan, arrs, on_dev))
        outs, exc = self._chain_dispatch(self.fusion_buffer, steps)
        exec_s = time.perf_counter() - _dt0
        if timed:
            self._perf_exec_s += exec_s
        disp1 = time.time() if self.tracer is not None else 0.0
        done = 0
        all_names: list = []
        total_bytes = 0
        last_token = None
        for i, parts in enumerate(outs):
            names, cplan, on_dev, nbytes, dtype = plan.chunks[i]
            all_names.extend(names)
            total_bytes += nbytes
            if parts:
                last_token = parts[0]
            m_bytes, m_lat, m_ops = self._op_metrics("allreduce", dtype)
            m_bytes.inc(nbytes)
            m_ops.inc()
            m_lat.observe(exec_s)
            self._m_fusion_batch.observe(len(names))
            self._m_fused_bytes.observe(nbytes)
            for n, p in zip(names, parts):
                e = by[n]
                if e.span is not None:
                    e.span.t[tracing_mod.T_DISPATCH_END] = disp1
                    e.span.chunk_bytes = nbytes
                    e.span.chunk_tensors = len(names)
                self._finish(e, p)
            done += len(names)
        self.bytes_processed += total_bytes
        # dispatch-phase window ends after completion bookkeeping so the
        # ledger attribution matches the negotiated path, whose timed
        # window also covers per-chunk metrics and entry finishing
        t_disp = time.perf_counter() - _dt0
        if exc is not None:
            # mid-chain failure: chain_dispatch already retired the
            # failing chunk's lease, so the ring is clean — fail every
            # remaining entry through the single terminal (zero leaked
            # spans) and degrade to negotiated mode
            self._m_op_errors.inc(len(batch) - done)
            err = HorovodInternalError(f"megaplan replay failed: {exc}")
            for names, _cplan, _od, _nb, _dt in plan.chunks[len(outs):]:
                for n in names:
                    self._finish(by[n], None, err)
            failing = plan.chunks[len(outs)]
            for n in failing[0]:
                self._finish(by[n], None, err)
            mp.invalidate("dispatch")
            self._finish_cycle(batch, cycle_t0, timed, t_neg, t_disp)
            return True
        mp.note_replay()
        if self.profiler is not None:
            self.profiler.note_megaplan(
                all_names, total_bytes, len(batch), exec_s,
                token=last_token, t0_pc=_dt0)
        self._finish_cycle(batch, cycle_t0, timed, t_neg, t_disp)
        return True

    def _negotiate(self, batch: list[TensorEntry]) -> list[TensorEntry]:
        """One negotiation round: post the pending set, receive the
        globally-ready ordered list (reference ComputeResponseList slow
        path, controller.cc:238-420). Runs every cycle — empty posts keep
        the lockstep rounds advancing for ranks that have nothing pending.
        """
        from .controller import entry_signature

        self._m_neg_rounds.inc()
        for e in batch:
            self._pending[self._wire_name(e)] = e
        sigs = {n: entry_signature(e) for n, e in self._pending.items()}
        rnd = self.controller.round
        if self.tracer is not None and self._pending:
            now = time.time()
            for e in self._pending.values():
                # first round only: a tensor pending across rounds keeps
                # the timestamp of the round that first carried it
                if e.span is not None \
                        and e.span.t[tracing_mod.T_NEG_START] is None:
                    e.span.t[tracing_mod.T_NEG_START] = now
                    e.span.round = rnd
        if self.recorder is not None:
            self.recorder.note("negotiation_round", state="begin",
                               round=rnd, tensors=len(sigs))
        if self.watchdog is not None:
            # an in-flight negotiation blocks the cycle loop by design;
            # the phase bracket lets a fire name it (vs a dead loop)
            self.watchdog.enter("negotiate")
        ok = False
        try:
            resp = self.controller.negotiate(sigs, joined=self.joined)
            ready, errors = resp["ready"], resp["errors"]
            ok = True
        except Exception as exc:
            # Fail everything — including on shutdown: a silent return would
            # leak handles a caller may be blocked on in hvd.wait().
            if self._stop.is_set():
                err: Exception = HorovodInternalError("Horovod has been shut down")
            else:
                LOG.error("negotiation failed: %s", exc)
                err = HorovodInternalError(
                    f"controller negotiation failed: {exc}")
            for e in self._pending.values():
                self._finish(e, None, err)
            self._pending.clear()
            return []
        finally:
            if self.watchdog is not None:
                self.watchdog.exit_phase("negotiate")
            if self.recorder is not None:
                self.recorder.note("negotiation_round", state="end",
                                   round=rnd, ok=ok)
        return self._process_response(resp)

    def _process_response(self, resp: dict) -> list[TensorEntry]:
        """Apply one negotiation response to the pending table: fail
        errored entries, record straggler verdicts, pop the ready set in
        coordinator order, fabricate joined zero-contributions, and note
        join completion. Shared by `_negotiate` and the megaplan
        lease-drop fallback — a dropped lease still consumed its round,
        so its response must flow through the identical path."""
        ready, errors = resp["ready"], resp["errors"]
        for n, msg in errors.items():
            e = self._pending.pop(n, None)
            if e is not None:
                self._m_neg_errors.inc()
                self._finish(e, None, HorovodInternalError(msg))
        out = []
        strag = resp.get("strag") or {}
        if (self.ledger is not None or self.profiler is not None) and strag:
            # worst verdict this round feeds the step record's straggler
            # field (the ledger decides whether it counts as stall)
            self._perf_strag = max(
                ((int(r), float(w)) for r, w in strag.values()),
                key=lambda rw: rw[1])
        neg_end = time.time() if self.tracer is not None else 0.0
        for n in ready:
            if n in self._pending:
                e = self._pending.pop(n)
                if e.span is not None:
                    e.span.t[tracing_mod.T_NEG_END] = neg_end
                    info = strag.get(n)
                    if info:
                        e.span.straggler_rank = int(info[0])
                        e.span.straggler_wait_s = float(info[1])
                        if self.stall:
                            self.stall.note_straggler(
                                e.name, int(info[0]), float(info[1]))
                out.append(e)
            elif self.joined:
                # fabricate a zero contribution from the coordinator's
                # signature (reference: joined ranks contribute zeros,
                # global_state.h:107-111). handle=-1: no caller is waiting.
                # Never for a sub-process-set this rank is not in.
                sig = resp["sigs"].get(n)
                if sig is not None and self._member_of_sig(sig):
                    out.append(self._zero_entry_from_sig(n, sig))
        if resp.get("join_done") is not None:
            self._join_last_rank = int(resp["join_done"])
            self.joined = False
            self._join_done_evt.set()
        return out

    def _member_of_sig(self, sig: list) -> bool:
        if len(sig) <= 9 or not sig[9]:
            return True  # global set: everyone is a member
        return self.process_set.cross_rank in set(sig[9])

    @staticmethod
    def _wire_name(e: TensorEntry) -> str:
        """Negotiation key: plain name for the global set, scoped by the
        process-set name otherwise — tensors on DIFFERENT sets may share
        a user name legitimately (reference keeps one message table per
        ProcessSet) and must not collide into a signature mismatch."""
        ps = e.process_set
        pname = getattr(ps, "name", None)
        return e.name if not pname or pname == "global" \
            else f"ps:{pname}:{e.name}"

    @staticmethod
    def _zero_entry_from_sig(name: str, sig: list) -> TensorEntry:
        """Build a zero-valued TensorEntry matching another rank's submitted
        signature ([op, dtype, shape, reduce_op, root, pre, post, ps, dev]).
        Allgather contributes an empty first dim (ragged support makes the
        zero-row contribution exact, not padded)."""
        op, dtype, shape = sig[0], sig[1], list(sig[2])
        if op in ("allgather", "alltoall") and shape:
            shape[0] = 0  # ragged ops: the sig's first dim is the "*" mark
        ps = None
        plain = name
        if sig[7] and sig[7] != "global":
            from ..common import context as ctx_mod

            ps = ctx_mod.context().process_sets.get(sig[7])
            # decode by the SIGNATURE, not a name prefix: a global tensor
            # whose user name merely starts with "ps:" must stay verbatim
            scope = f"ps:{sig[7]}:"
            if name.startswith(scope):
                plain = name[len(scope):]
        return TensorEntry(
            name=plain, op=op, tensor=np.zeros(shape, dtype=np.dtype(dtype)),
            reduce_op=C.ReduceOp(sig[3]), root_rank=sig[4],
            prescale_factor=sig[5], postscale_factor=sig[6], process_set=ps)

    def join(self, timeout: Optional[float] = None) -> int:
        """Reference hvd.join(): mark this rank out of data, keep
        contributing zeros to other ranks' collectives, block until every
        rank has joined; returns the last rank to join."""
        if self.controller is None:
            return self.process_set.rank
        self._join_done_evt.clear()
        self.joined = True
        self._wake.set()
        if not self._join_done_evt.wait(timeout or 600.0):
            self.joined = False
            raise HorovodInternalError("join() timed out waiting for all ranks")
        return self._join_last_rank

    # -- execution -----------------------------------------------------------
    def _finish(self, entry: TensorEntry, result, exc=None):
        self.queue.release(entry.name)
        if self.stall:
            self.stall.record_done(entry.name)
        if self.timeline:
            self.timeline.negotiate_end(entry.name)
        if entry.span is not None and self.tracer is not None:
            # the single terminal: every execution/negotiation/stall/
            # shutdown path converges here, so spans cannot leak open
            self.tracer.finish(entry.span, error=exc is not None)
            entry.span = None
        self.handles.mark_done(entry.handle, result, exc)

    def _quant_spec_for(self, group: list[TensorEntry]):
        """Effective quantization spec for a fused group: a per-call
        marker wins (the group key guarantees it is uniform), else the
        HOROVOD_COMPRESSION runtime default. One or-check when both are
        None — the zero-cost contract."""
        return group[0].quant or self._quant

    def _quant_split(self, group: list[TensorEntry], spec):
        """Partition a fused group into (quantized, uncompressed) per the
        convergence guardrails: name-pattern opt-outs, the small-leaf
        threshold, non-float dtypes — and worlds with no wire to
        compress. Every fallback decision is counted
        (hvd_quant_fallback_total{reason}) and noted once per tensor
        name in the flight recorder, so a postmortem bundle explains
        surprising wire bytes."""
        if self._quant_optout is None:  # lazy: first quantized group
            self._quant_optout = compression_mod.quant_optout_patterns()
            self._quant_min_elems = compression_mod.quant_min_elems()
            self._quant_residuals = compression_mod.ResidualStore()

        def _fallback(e, reason):
            mark = (e.name, reason)
            if mark not in self._quant_noted:
                self._quant_noted.add(mark)
                compression_mod.quant_fallback_counter(reason).inc()
                flightrec_mod.note("quant_fallback", name=e.name,
                                   reason=reason)

        ps = group[0].process_set or self.process_set
        if ps.cross_size <= 1 or not self._plans_enabled:
            # no wire to compress (or plans off): the whole group stays
            # uncompressed; a single-process run is how the zero-cost
            # tests drive the runtime, so note it like any other fallback
            for e in group:
                _fallback(e, "world_size" if ps.cross_size <= 1
                          else "plans_disabled")
            return [], group
        quant, plain = [], []
        for e in group:
            t = e.tensor
            size = int(getattr(t, "size", None) or np.asarray(t).size)
            reason = compression_mod.quant_fallback_reason(
                e.name, size, getattr(t, "dtype", "float32"),
                self._quant_optout, self._quant_min_elems)
            if reason is None:
                quant.append(e)
            else:
                _fallback(e, reason)
                plain.append(e)
        return quant, plain

    def _chunk_group(self, group: list[TensorEntry]) -> list[list[TensorEntry]]:
        """Split a fusable group into dispatch chunks: byte-bounded by the
        fusion threshold and (when ``plan_chunk_tensors`` > 0) capped at
        that many tensors per chunk — the autotuner's granularity knob."""
        chunk: list[TensorEntry] = []
        nbytes = 0
        chunks = []
        cap = self.plan_chunk_tensors
        for e in group:
            sz = getattr(e.tensor, "nbytes", None)
            if sz is None:  # explicit None check: nbytes == 0 is valid
                sz = np.asarray(e.tensor).nbytes
            if chunk and (nbytes + sz > self.fusion_threshold
                          or (cap and len(chunk) >= cap)):
                chunks.append(chunk)
                chunk, nbytes = [], 0
            chunk.append(e)
            nbytes += sz
        if chunk:
            chunks.append(chunk)
        return chunks

    def _run_fused_allreduce(self, group: list[TensorEntry]):
        """Fuse up to fusion_threshold bytes into one flat compiled psum
        (the MEMCPY_IN_FUSION_BUFFER → op → MEMCPY_OUT of
        collective_operations.h:65-88, done by XLA as concat/slice fusion)."""
        spec = self._quant_spec_for(group)
        if spec is not None:
            qgroup, group = self._quant_split(group, spec)
            if qgroup:
                self._run_quant_allreduce(qgroup, spec)
            if not group:
                return
        for chunk in self._chunk_group(group):
            names = [e.name for e in chunk]
            t0 = time.perf_counter()
            if self.timeline:
                for n in names:
                    self.timeline.start_activity(n, "FUSED_ALLREDUCE")
            try:
                # device-resident chunk: fuse on device instead of the
                # host fusion buffer — gradients that already live in HBM
                # never round-trip through the host (reference NCCL path
                # reduces the GPU buffer in place)
                on_dev = all(C.is_device_resident(e.tensor) for e in chunk)
                if on_dev:
                    arrs = [e.tensor for e in chunk]
                else:
                    arrs = [np.asarray(e.tensor) for e in chunk]
                e0 = chunk[0]
                ps = e0.process_set or self.process_set
                sizes = tuple(int(a.size) for a in arrs)
                shapes = tuple(tuple(a.shape) for a in arrs)
                dtype = str(arrs[0].dtype)
                total_bytes = sum(int(a.nbytes) for a in arrs)
                # steady-state fast path: replay the compiled plan for this
                # chunk signature — one program dispatch covering
                # pack+reduce+unpack (falls back to the eager chain when
                # disabled or for zero-element chunks)
                plan = None
                if self._plans_enabled:
                    plan = C.fused_chunk_plan(
                        ps, e0.reduce_op, e0.prescale_factor,
                        e0.postscale_factor, tuple(names), sizes, shapes,
                        dtype, on_dev)
                cap = self._mp_capture
                if cap is not None:
                    if type(plan) is C.FusedChunkPlan:
                        # record this chunk's schedule step; the plan
                        # object is an owned reference, so later LRU
                        # eviction cannot tear a live megaplan
                        cap.append((tuple(names), plan, on_dev,
                                    total_bytes, dtype))
                    else:
                        # legacy eager chain / zero-element chunk: the
                        # step is not whole-step replayable
                        self._mp_capture = None
                if self.tracer is not None:
                    disp0 = time.time()
                    for e in chunk:
                        if e.span is not None:
                            e.span.t[tracing_mod.T_DISPATCH_START] = disp0
                            e.span.chunk_bytes = total_bytes
                            e.span.chunk_tensors = len(chunk)
                if self.ledger is not None or self.profiler is not None:
                    _xt = time.perf_counter()
                faults_mod.fault_point("plan.dispatch")
                if plan is not None:
                    parts = self._dispatch_plan(plan, arrs, on_dev)
                else:
                    parts = self._dispatch_legacy(arrs, on_dev, e0, ps,
                                                  sizes, shapes)
                if self.ledger is not None:
                    self._perf_exec_s += time.perf_counter() - _xt
                if self.profiler is not None:
                    self.profiler.note_chunk(
                        names, total_bytes, len(chunk),
                        time.perf_counter() - _xt,
                        token=parts[0] if parts else None, t0_pc=_xt)
                if self.tracer is not None:
                    disp1 = time.time()
                    for e in chunk:
                        if e.span is not None:
                            e.span.t[tracing_mod.T_DISPATCH_END] = disp1
                self.bytes_processed += total_bytes
                m_bytes, m_lat, m_ops = self._op_metrics("allreduce", dtype)
                m_bytes.inc(total_bytes)
                m_ops.inc()
                m_lat.observe(time.perf_counter() - t0)
                self._m_fusion_batch.observe(len(chunk))
                self._m_fused_bytes.observe(total_bytes)
                # results stay device-side lazy values: the cycle thread
                # must not block on completion (async contract; callers
                # observe readiness per-handle)
                for e, p in zip(chunk, parts):
                    self._finish(e, p)
            except Exception as exc:  # fail the whole chunk
                self._mp_capture = None  # a failed chunk is uncapturable
                self._m_op_errors.inc(len(chunk))
                for e in chunk:
                    self._finish(e, None,
                                 HorovodInternalError(f"fused allreduce failed: {exc}"))
            finally:
                if self.timeline:
                    for n in names:
                        self.timeline.end_activity(n)

    def _dispatch_plan(self, plan, arrs, on_dev):
        """One-dispatch chunk execution. Host chunks stage through a leased
        ring slot; the lease is retired with one of the plan's outputs as
        completion token, so the slot frees exactly when the compiled
        program has consumed the staged bytes (never earlier — the async
        transfer, or a CPU-backend zero-copy alias, may still be reading)."""
        if on_dev:
            return plan.execute(arrs)
        flat, lease = self.fusion_buffer.pack_leased(arrs)
        try:
            parts = plan.execute(flat)
        except Exception:
            # failed dispatch: results are discarded, so an immediate free
            # cannot corrupt anything a caller will observe
            if lease is not None:
                lease.retire(None)
            raise
        if lease is not None:
            lease.retire(parts[0])
        return parts

    def _run_quant_allreduce(self, group: list[TensorEntry], spec):
        """Quantized flavor of ``_run_fused_allreduce``: same chunking,
        same one-program steady state, but the chunk replays a
        QuantFusedChunkPlan — quantize→stage→dequantize→reduce→unpack
        with only packed payload + scale words on the wire.

        Error-feedback lifecycle: the residual for a chunk (keyed by its
        ordered tensor names + quant signature) is read before dispatch
        and committed only AFTER the compiled program ran — a failed or
        retried dispatch leaves the previous carry in place, so the
        error is never double-applied (tests/test_quantized.py chaos
        coverage). The store itself resets on elastic-generation change
        (compression.ResidualStore)."""
        # the residual read-then-commit lifecycle has per-dispatch state a
        # captured schedule could not replay safely: quant steps opt out
        # of whole-step capture
        self._mp_capture = None
        store = self._quant_residuals
        for chunk in self._chunk_group(group):
            names = [e.name for e in chunk]
            t0 = time.perf_counter()
            if self.timeline:
                for n in names:
                    self.timeline.start_activity(n, "QUANT_FUSED_ALLREDUCE")
            try:
                on_dev = all(C.is_device_resident(e.tensor) for e in chunk)
                if on_dev:
                    arrs = [e.tensor for e in chunk]
                else:
                    arrs = [np.asarray(e.tensor) for e in chunk]
                e0 = chunk[0]
                ps = e0.process_set or self.process_set
                sizes = tuple(int(a.size) for a in arrs)
                shapes = tuple(tuple(a.shape) for a in arrs)
                dtype = str(arrs[0].dtype)
                total_bytes = sum(int(a.nbytes) for a in arrs)
                plan = C.fused_chunk_plan(
                    ps, e0.reduce_op, e0.prescale_factor,
                    e0.postscale_factor, tuple(names), sizes, shapes,
                    dtype, on_dev, quant=spec)
                if self.tracer is not None:
                    disp0 = time.time()
                    for e in chunk:
                        if e.span is not None:
                            e.span.t[tracing_mod.T_DISPATCH_START] = disp0
                            e.span.chunk_bytes = total_bytes
                            e.span.chunk_tensors = len(chunk)
                if self.profiler is not None:
                    _xt = time.perf_counter()
                faults_mod.fault_point("plan.dispatch")
                if isinstance(plan, C.QuantFusedChunkPlan):
                    rkey = (tuple(names), spec.signature())
                    residual = (store.get(rkey, plan.flat_size)
                                if spec.error_feedback else None)
                    parts, new_res = plan.execute(arrs, residual)
                    if new_res is not None:
                        # commit AFTER the dispatch succeeded — see
                        # docstring
                        store.commit(rkey, new_res)
                    compression_mod.record_quant_chunk(
                        plan.pre_bytes, plan.wire_bytes, spec.bits,
                        plan.n_blocks)
                elif isinstance(plan, C.CastFusedChunkPlan):
                    # bf16 cast wire: no scales, no residual lifecycle
                    parts = plan.execute(arrs)
                    compression_mod.record_quant_chunk(
                        plan.pre_bytes, plan.wire_bytes, spec.bits, 0)
                elif plan is not None:
                    # fused_chunk_plan declined the quant flavor (e.g. an
                    # unsupported op slipped through): plain plan dispatch
                    parts = self._dispatch_plan(plan, arrs, on_dev)
                else:
                    parts = self._dispatch_legacy(arrs, on_dev, e0, ps,
                                                  sizes, shapes)
                if self.profiler is not None:
                    self.profiler.note_chunk(
                        names, total_bytes, len(chunk),
                        time.perf_counter() - _xt,
                        token=parts[0] if parts else None, t0_pc=_xt)
                if self.tracer is not None:
                    disp1 = time.time()
                    for e in chunk:
                        if e.span is not None:
                            e.span.t[tracing_mod.T_DISPATCH_END] = disp1
                self.bytes_processed += total_bytes
                m_bytes, m_lat, m_ops = self._op_metrics("allreduce", dtype)
                m_bytes.inc(total_bytes)
                m_ops.inc()
                m_lat.observe(time.perf_counter() - t0)
                self._m_fusion_batch.observe(len(chunk))
                self._m_fused_bytes.observe(total_bytes)
                for e, p in zip(chunk, parts):
                    self._finish(e, p)
            except Exception as exc:
                self._m_op_errors.inc(len(chunk))
                for e in chunk:
                    self._finish(e, None, HorovodInternalError(
                        f"quantized fused allreduce failed: {exc}"))
            finally:
                if self.timeline:
                    for n in names:
                        self.timeline.end_activity(n)

    def _dispatch_legacy(self, arrs, on_dev, e0, ps, sizes, shapes):
        """Pre-plan eager chain (kept as the HOROVOD_FUSED_PLAN_DISABLE
        fallback and for zero-element chunks): per-tensor ravels + concat
        (device) or fresh-buffer pack (host), a cached reduce program, and
        a separate jitted unpack dispatch (collectives.unpack_flat)."""
        import jax.numpy as _jnp

        if on_dev:
            flats = [_jnp.ravel(a) for a in arrs]
            fused = flats[0] if len(flats) == 1 \
                else _jnp.concatenate(flats)
        else:
            if len(arrs) > 1:
                fused = self.fusion_buffer.pack(arrs)
            else:
                fused = arrs[0].ravel()
        red = C._eager_allreduce(fused, e0.reduce_op, ps,
                                 e0.prescale_factor, e0.postscale_factor)
        return C.unpack_flat(red, sizes, shapes)

    def _run_single(self, e: TensorEntry):
        t0 = time.perf_counter()
        if self.timeline:
            self.timeline.start_activity(e.name, e.op.upper())
        if e.span is not None:
            e.span.t[tracing_mod.T_DISPATCH_START] = time.time()
        try:
            ps = e.process_set or self.process_set
            if self.profiler is not None:
                _xt = time.perf_counter()
            faults_mod.fault_point("plan.dispatch")
            if e.op == "allreduce":
                r = C._eager_allreduce(e.tensor, e.reduce_op, ps,
                                       e.prescale_factor, e.postscale_factor)
            elif e.op == "allgather":
                r = C._eager_allgather(e.tensor, ps)
            elif e.op == "broadcast":
                r = C._eager_broadcast(e.tensor, e.root_rank, ps)
            elif e.op == "alltoall":
                r = C._eager_alltoall(e.tensor, e.splits, ps)
            elif e.op == "reducescatter":
                r = C._eager_reducescatter(e.tensor, e.reduce_op, ps)
            else:
                raise HorovodInternalError(f"unknown op {e.op}")
            t = e.tensor
            nbytes = getattr(t, "nbytes", None)
            if nbytes is None:
                nbytes = np.asarray(t).nbytes
            self.bytes_processed += nbytes
            if self.profiler is not None:
                self.profiler.note_chunk(
                    [e.name], int(nbytes), 1, time.perf_counter() - _xt,
                    token=r if hasattr(r, "is_ready") else None, t0_pc=_xt)
            m_bytes, m_lat, m_ops = self._op_metrics(
                e.op, str(getattr(t, "dtype", None) or np.asarray(t).dtype))
            m_bytes.inc(int(nbytes))
            m_ops.inc()
            m_lat.observe(time.perf_counter() - t0)
            if e.span is not None:
                e.span.t[tracing_mod.T_DISPATCH_END] = time.time()
            self._finish(e, r)
        except Exception as exc:
            self._m_op_errors.inc()
            self._finish(e, None, HorovodInternalError(str(exc)))
        finally:
            if self.timeline:
                self.timeline.end_activity(e.name)
