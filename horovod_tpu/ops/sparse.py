"""Sparse gradient collectives (embedding-style updates).

Reference: Horovod reduces sparse gradients by allgathering values+indices
instead of densifying — TF IndexedSlices path
(/root/reference/horovod/tensorflow/__init__.py:92-108) and
torch ``sparse_allreduce_async`` (torch/mpi_ops.py:512).

TPU-shaped equivalents:

- `sparse_allreduce` (traced): allgather values and indices over the mesh
  axis and return the concatenated (ragged-free: per-chip counts are equal
  under SPMD) slices — the average is deferred to the consumer like the
  reference's IndexedSlices/n.
- `sparse_to_dense_allreduce` (traced): scatter-add into the dense shape
  then one psum — often *faster* on TPU when the dense dim fits HBM,
  because one fused psum beats gather+host math; provided because the
  right choice is workload-dependent (reference docs call this the
  `sparse_as_dense` DistributedOptimizer option).
- eager path: ragged allgather via the process collectives.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.context import DEFAULT_AXIS
from . import collectives as C


class IndexedSlices(NamedTuple):
    """values[k, ...] to be added at rows indices[k] of a dense tensor."""

    values: jax.Array
    indices: jax.Array
    dense_rows: int


def sparse_allreduce(slices: IndexedSlices, *, average: bool = True,
                     axis_name: str = DEFAULT_AXIS) -> IndexedSlices:
    """Allgather-based sparse reduction (reference IndexedSlices path).

    Returns gathered slices; duplicate indices are legal (consumers apply
    scatter-add), matching IndexedSlices semantics.
    """
    if C._is_traced(slices.values):
        n = lax.axis_size(axis_name)
        values = C._traced_allgather(slices.values, axis_name)
        indices = C._traced_allgather(slices.indices, axis_name)
    else:
        n = C._ps(None).cross_size
        values = C.allgather(slices.values)
        indices = C.allgather(slices.indices)
    if average:
        values = values / n
    return IndexedSlices(values, indices, slices.dense_rows)


def sparse_to_dense_allreduce(slices: IndexedSlices, *, average: bool = True,
                              axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """Densify + psum (the `sparse_as_dense` option): scatter-add locally,
    one fused collective globally."""
    dense = jnp.zeros((slices.dense_rows,) + slices.values.shape[1:],
                      slices.values.dtype)
    dense = dense.at[slices.indices].add(slices.values)
    op = C.ReduceOp.AVERAGE if average else C.ReduceOp.SUM
    return C.allreduce(dense, op=op, axis_name=axis_name)


def apply_indexed_slices(dense, slices: IndexedSlices):
    """Scatter-add slices into a dense tensor (consumer-side helper)."""
    return dense.at[slices.indices].add(slices.values)
