"""Adasum: scale-invariant adaptive summation of gradients.

Reference: /root/reference/horovod/common/ops/adasum/adasum.h — recursive
vector-halving distance-doubling with per-pair dot products and squared norms
(`DispatchComputeDotAndNormSqrds` adasum.h:101, `DispatchScaledAdd` :124),
MPI point-to-point for the exchange.

TPU-native redesign: the same hypercube recursion expressed as
``log2(n)`` rounds of ``lax.ppermute`` over a mesh axis (no point-to-point —
ICI neighbor exchange *is* ppermute), with the combine rule computed on-chip
in float32. The pair combine for gradients a, b is:

    result = (1 - a.b / (2 |a|^2)) * a  +  (1 - a.b / (2 |b|^2)) * b

which reduces to a simple sum for orthogonal gradients and to the average
for identical ones (adasum.h:38 design comment).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def adasum_combine(a, b, norm_axis: str | None = None):
    """Combine two same-shaped gradient tensors with the Adasum rule.

    Computed in float32 for stability (reference uses double accumulators
    for fp16 inputs, adasum.h AVX F16C paths), cast back to input dtype.

    ``norm_axis``: when ``a``/``b`` are *chunks* of a vector scattered
    over a mesh axis, the dot products and norms must describe the FULL
    vector for the combine coefficients to match unchunked Adasum — so
    the three scalars are psummed over that axis before use (exactly the
    reference's fused scheme: local partial dots + an allreduce of the
    double[3], adasum.h DotProdImpl / adasum_mpi.cc).
    """
    dt = a.dtype
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    na2 = jnp.vdot(af, af)
    nb2 = jnp.vdot(bf, bf)
    if norm_axis is not None:
        dot, na2, nb2 = lax.psum(jnp.stack([dot, na2, nb2]), norm_axis)
    # zero-norm edges: if a == 0 result is b, and vice versa
    acoef = jnp.where(na2 > 0, 1.0 - dot / (2.0 * jnp.where(na2 > 0, na2, 1.0)), 0.0)
    bcoef = jnp.where(nb2 > 0, 1.0 - dot / (2.0 * jnp.where(nb2 > 0, nb2, 1.0)), 0.0)
    return (acoef * af + bcoef * bf).astype(dt)


def adasum_allreduce(x, axis_name: str, norm_axis: str | None = None):
    """Traced Adasum allreduce over a mesh axis (power-of-2 size).

    Hypercube distance-doubling: round k exchanges with partner
    ``rank XOR 2^k`` via ``ppermute``; the combine rule is symmetric so both
    partners converge to the same value — after log2(n) rounds every chip
    holds the full Adasum reduction (replaces adasum.h:161 recursion +
    MPI_Send/Recv with XLA collectives).

    ``norm_axis``: see adasum_combine — set when ``x`` is a chunk of a
    vector scattered over that other axis (the hierarchical path).
    """
    n = lax.axis_size(axis_name)
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-2 group size, got {n}")
    k = 1
    while k < n:
        perm = [(i, i ^ k) for i in range(n)]
        other = lax.ppermute(x, axis_name, perm)
        x = adasum_combine(x, other, norm_axis=norm_axis)
        k *= 2
    # All chips now hold the identical reduction, but ppermute outputs are
    # typed as device-varying; the closing pmean of identical values is a
    # no-op numerically and re-types the result as replicated so it can
    # cross shard_map boundaries with out_specs=P().
    return lax.pmean(x, axis_name)


def adasum_allreduce_hierarchical(x, local_axis: str, cross_axis: str):
    """Two-level Adasum over the mesh triad (reference
    adasum_gpu_operations.cc:1-319: NCCL ReduceScatter within the node →
    Adasum across nodes on the scattered chunks → NCCL Allgather).

    TPU mapping: mean + scatter over the ICI-local axis
    (``psum_scatter / n_local`` — local contributions average, like the
    reference's LR-scaling contract that treats the node as one
    logical contributor), then the cross-axis hypercube runs on 1/n_local
    chunks with the dot/norm scalars psummed over the local axis — so the
    combine coefficients describe the full vectors and the result equals
    unchunked Adasum of the local means, while cross-axis (DCN) traffic
    per chip drops by n_local. The closing all_gather is the local
    broadcast.
    """
    nl = lax.axis_size(local_axis)
    flat = x.reshape(-1)
    pad = (-flat.size) % nl
    padded = jnp.pad(flat, (0, pad))
    chunk = lax.psum_scatter(padded, local_axis, scatter_dimension=0,
                             tiled=True) / nl
    red = adasum_allreduce(chunk, cross_axis, norm_axis=local_axis)
    full = lax.all_gather(red, local_axis, tiled=True)
    return full[:flat.size].reshape(x.shape)


def adasum_tree_reduce(g):
    """Eager-path Adasum over a stacked array g[n, ...] (single compiled
    program; used by the per-process eager collective)."""
    n = g.shape[0]
    while n > 1:
        half = (n + 1) // 2
        even = g[0:2 * (n // 2):2]
        odd = g[1:2 * (n // 2):2]
        combined = jax.vmap(adasum_combine)(even, odd)
        if n % 2:
            combined = jnp.concatenate([combined, g[n - 1 : n]], axis=0)
        g = combined
        n = half
    return g[0]
