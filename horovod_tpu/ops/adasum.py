"""Adasum: scale-invariant adaptive summation of gradients.

Reference: /root/reference/horovod/common/ops/adasum/adasum.h — recursive
vector-halving distance-doubling with per-pair dot products and squared norms
(`DispatchComputeDotAndNormSqrds` adasum.h:101, `DispatchScaledAdd` :124),
MPI point-to-point for the exchange.

TPU-native redesign: the same hypercube recursion expressed as
``log2(n)`` rounds of ``lax.ppermute`` over a mesh axis (no point-to-point —
ICI neighbor exchange *is* ppermute), with the combine rule computed on-chip
in float32. The pair combine for gradients a, b is:

    result = (1 - a.b / (2 |a|^2)) * a  +  (1 - a.b / (2 |b|^2)) * b

which reduces to a simple sum for orthogonal gradients and to the average
for identical ones (adasum.h:38 design comment).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def adasum_combine(a, b):
    """Combine two same-shaped gradient tensors with the Adasum rule.

    Computed in float32 for stability (reference uses double accumulators
    for fp16 inputs, adasum.h AVX F16C paths), cast back to input dtype.
    """
    dt = a.dtype
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    na2 = jnp.vdot(af, af)
    nb2 = jnp.vdot(bf, bf)
    # zero-norm edges: if a == 0 result is b, and vice versa
    acoef = jnp.where(na2 > 0, 1.0 - dot / (2.0 * jnp.where(na2 > 0, na2, 1.0)), 0.0)
    bcoef = jnp.where(nb2 > 0, 1.0 - dot / (2.0 * jnp.where(nb2 > 0, nb2, 1.0)), 0.0)
    return (acoef * af + bcoef * bf).astype(dt)


def adasum_allreduce(x, axis_name: str):
    """Traced Adasum allreduce over a mesh axis (power-of-2 size).

    Hypercube distance-doubling: round k exchanges with partner
    ``rank XOR 2^k`` via ``ppermute``; the combine rule is symmetric so both
    partners converge to the same value — after log2(n) rounds every chip
    holds the full Adasum reduction (replaces adasum.h:161 recursion +
    MPI_Send/Recv with XLA collectives).
    """
    n = lax.axis_size(axis_name)
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-2 group size, got {n}")
    k = 1
    while k < n:
        perm = [(i, i ^ k) for i in range(n)]
        other = lax.ppermute(x, axis_name, perm)
        x = adasum_combine(x, other)
        k *= 2
    # All chips now hold the identical reduction, but ppermute outputs are
    # typed as device-varying; the closing pmean of identical values is a
    # no-op numerically and re-types the result as replicated so it can
    # cross shard_map boundaries with out_specs=P().
    return lax.pmean(x, axis_name)


def adasum_tree_reduce(g):
    """Eager-path Adasum over a stacked array g[n, ...] (single compiled
    program; used by the per-process eager collective)."""
    n = g.shape[0]
    while n > 1:
        half = (n + 1) // 2
        even = g[0:2 * (n // 2):2]
        odd = g[1:2 * (n // 2):2]
        combined = jax.vmap(adasum_combine)(even, odd)
        if n % 2:
            combined = jnp.concatenate([combined, g[n - 1 : n]], axis=0)
        g = combined
        n = half
    return g[0]
