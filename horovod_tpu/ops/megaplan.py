"""Whole-step megaplan capture & replay: the Python-free steady state.

The fused-plan cache (ops/collectives.py) already collapses each chunk
to one compiled dispatch, but the cycle loop still pays per-step Python
for negotiation, ordering, grouping and per-chunk plan lookups — the
`replay_headroom_s` the step-anatomy profiler (utils/anatomy.py)
measures. This module removes it: when the runtime observes the
identical named tensor set for ``HOROVOD_MEGAPLAN_STABLE_ROUNDS``
consecutive working cycles (the same stability the controller's
response-cache/SAME_AS_LAST wire marker detects), it captures the whole
step's collective schedule — negotiated order, fused-chunk grouping,
and the compiled chunk programs from the plan LRU — as one
epoch-guarded :class:`Megaplan`. Steady-state cycles then replay it
through ``_native.chain_dispatch`` with ~a single is-valid check.

Validity is epoch-guarded on two axes so correctness never depends on
replay:

- the **megaplan epoch** (:func:`epoch`), bumped by
  :func:`invalidate_megaplan` from every autotuner knob setter, plan
  cache invalidation, and hier-topology change;
- the **plan epoch** (collectives._plan_epoch, the elastic generation),
  stamped at capture so an elastic resize invalidates within one cycle.

Any mismatch — epoch, batch signature (names/shapes/dtypes/ops/
residency), membership (join, pending backlog), or a dropped
coordinator lease — atomically degrades the cycle back to the
negotiated path and re-arms capture.

Multi-process entry/exit is round-synchronized by a coordinator
**lease**: the coordinator counts consecutive all-marker rounds
(every rank submitted the 1-byte SAME_AS_LAST wire) and grants ``mp``
on its response; any rank breaking stability (a full payload, an
error, a join, a params push) drops the lease for everyone in the same
round (ops/controller.py).

Zero-cost contract (same as utils/anatomy.py, enforced by
benchmarks/megaplan_overhead.py): with ``HOROVOD_MEGAPLAN`` unset no
manager exists, ``ops/queue.py`` pays one ``is None`` check per cycle,
and no ``hvd_megaplan_*`` series is registered — metric handles are
resolved in ``MegaplanManager.__init__``, lazily at enable.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

from ..common import env as env_schema
from ..utils import flightrec as flightrec_mod

DEFAULT_STABLE_ROUNDS = 5

#: Megaplan epoch: bumped by every :func:`invalidate_megaplan` call.
#: Captured plans stamp the value they were built under; a steady-state
#: cycle compares one int — the "single is-valid check" of the replay
#: fast path. Plain int (CPython word-atomic): readers only compare.
_EPOCH = 0

_MANAGER: Optional["MegaplanManager"] = None


def epoch() -> int:
    return _EPOCH


def invalidate_megaplan(reason: str = "invalidation") -> None:
    """The single invalidation funnel (the ``invalidate_fused_plans()``
    of whole-step schedules): every autotuner knob setter, plan-cache
    invalidation, elastic transition and hier-topology change routes
    here. Bumps the epoch — so a replaying cycle thread fails its next
    validity check — and drops the captured plan."""
    global _EPOCH
    _EPOCH += 1
    mgr = _MANAGER
    if mgr is not None:
        mgr.invalidate(reason)


def batch_signature(batch: Sequence[Any]) -> Tuple:
    """Order-insensitive identity of a drained batch: (name, op, shape,
    dtype, reduce op, scales, process set, quant, residency) per entry,
    sorted by name. Replay compares the drained batch's signature to the
    captured one — a shape, dtype, membership or residency change under
    a reused name misses instead of executing a stale program."""
    from . import collectives as C

    rows = []
    for e in batch:
        t = e.tensor
        q = e.quant
        rows.append((e.name, e.op,
                     tuple(getattr(t, "shape", ()) or ()),
                     str(getattr(t, "dtype", "")),
                     int(e.reduce_op), float(e.prescale_factor),
                     float(e.postscale_factor),
                     getattr(e.process_set, "name", None) or "global",
                     None if q is None else q.signature(),
                     bool(C.is_device_resident(t))))
    rows.sort()
    return tuple(rows)


class Megaplan:
    """One captured whole-step schedule: the ordered chunk dispatch
    chain plus the validity stamps it was captured under."""

    __slots__ = ("sig", "chunks", "epoch", "plan_epoch", "tensors",
                 "nbytes")

    def __init__(self, sig: Tuple, chunks: Tuple, epoch: int,
                 plan_epoch: int):
        #: batch signature (see :func:`batch_signature`)
        self.sig = sig
        #: ordered chunk steps: (names, compiled plan, on_device,
        #: chunk bytes, dtype) — plan objects are owned references, so a
        #: later LRU eviction cannot tear a live megaplan
        self.chunks = chunks
        self.epoch = epoch
        self.plan_epoch = plan_epoch
        self.tensors = sum(len(c[0]) for c in chunks)
        self.nbytes = sum(int(c[3]) for c in chunks)


class MegaplanManager:
    """Capture/replay state for one runtime (cycle-thread driven).

    The state machine is armed → captured; ``observe()`` counts
    consecutive identical batch signatures on negotiated working
    cycles, ``commit()`` installs the captured schedule, and any
    validity miss or :func:`invalidate_megaplan` call drops it and
    re-arms. ``invalidate()`` may be called from other threads (elastic
    driver, autotuner apply path): it only clears references, so the
    cycle thread observes either the old plan (stale epoch → miss) or
    None."""

    def __init__(self, rank: int = 0, stable_rounds: Optional[int] = None):
        self.rank = rank
        if stable_rounds is None:
            stable_rounds = env_schema.get_int(
                env_schema.HOROVOD_MEGAPLAN_STABLE_ROUNDS,
                DEFAULT_STABLE_ROUNDS)
        self.stable_rounds = max(1, int(stable_rounds))
        self.plan: Optional[Megaplan] = None
        self._last_sig: Optional[Tuple] = None
        self._stable = 0
        #: stable cycles observed before the most recent capture
        self.capture_rounds = 0
        self.captures = 0
        self.replays = 0
        #: post-capture cycles that missed validity (the hit-rate
        #: denominator together with ``replays``)
        self.misses = 0
        self.invalidations = 0
        from ..utils import metrics as metrics_mod

        reg = metrics_mod.get_registry()
        self._reg = reg
        self._m_captures = reg.counter(
            "hvd_megaplan_captures_total",
            "whole-step megaplans captured")
        self._m_replays = reg.counter(
            "hvd_megaplan_replays_total",
            "steady-state cycles replayed from a captured megaplan")
        self._m_active = reg.gauge(
            "hvd_megaplan_active",
            "1 while a captured megaplan is live, 0 while armed")
        self._m_capture_rounds = reg.gauge(
            "hvd_megaplan_capture_rounds",
            "stable cycles observed before the most recent capture")
        # per-reason invalidation handles, lazily cached like the
        # queue's per-(op, dtype) metric dict
        self._m_inval: dict = {}

    # -- cycle-thread state machine ------------------------------------

    def observe(self, sig: Tuple) -> bool:
        """Count stability on a negotiated working cycle; True when the
        batch has been identical for ``stable_rounds`` consecutive
        cycles and no plan is live — i.e. THIS cycle should capture."""
        if sig == self._last_sig:
            self._stable += 1
        else:
            self._last_sig = sig
            self._stable = 1
        return self.plan is None and self._stable >= self.stable_rounds

    def commit(self, plan: Megaplan) -> None:
        """Install a captured schedule and note the event."""
        self.plan = plan
        self.captures += 1
        self.capture_rounds = self._stable
        self._m_captures.inc()
        self._m_active.set(1)
        self._m_capture_rounds.set(self.capture_rounds)
        flightrec_mod.note("megaplan", event="captured",
                           tensors=plan.tensors, chunks=len(plan.chunks),
                           bytes=plan.nbytes, rounds=self._stable)

    def abort_capture(self) -> None:
        """A capture attempt failed (injected fault, partial coverage):
        restart the stability count so re-capture needs a fresh
        stable window."""
        self._stable = 0
        self._last_sig = None

    def note_replay(self) -> None:
        self.replays += 1
        self._m_replays.inc()

    def invalidate(self, reason: str = "invalidation") -> None:
        """Drop the captured schedule (if any) and re-arm capture.
        Callable from any thread; counted only when a plan was live so
        repeated invalidations of an armed manager stay silent."""
        had = self.plan is not None
        self.plan = None
        self._stable = 0
        self._last_sig = None
        if not had:
            return
        self.invalidations += 1
        self.misses += 1
        m = self._m_inval.get(reason)
        if m is None:
            m = self._m_inval[reason] = self._reg.counter(
                "hvd_megaplan_invalidations_total",
                "captured megaplans dropped back to negotiated mode",
                reason=reason)
        m.inc()
        self._m_active.set(0)
        flightrec_mod.note("megaplan", event="invalidated", reason=reason)

    # -- readers --------------------------------------------------------

    def replay_hit_rate(self) -> Optional[float]:
        """Replayed fraction of post-capture steady-state cycles; None
        before the first capture attempt resolves."""
        total = self.replays + self.misses
        if total == 0:
            return None
        return self.replays / total

    def report(self) -> dict:
        plan = self.plan
        out = {"enabled": True, "active": plan is not None,
               "stable_rounds": self.stable_rounds,
               "captures": self.captures, "replays": self.replays,
               "misses": self.misses,
               "invalidations": self.invalidations,
               "capture_rounds": self.capture_rounds,
               "replay_hit_rate": self.replay_hit_rate(),
               "epoch": _EPOCH}
        if plan is not None:
            out["plan"] = {"tensors": plan.tensors,
                           "chunks": len(plan.chunks),
                           "bytes": plan.nbytes,
                           "epoch": plan.epoch,
                           "plan_epoch": plan.plan_epoch}
        return out


# --------------------------------------------------------------------------
# Process-global manager (the utils/anatomy.py module-trio pattern):
# get_manager() returns None when HOROVOD_MEGAPLAN is off, and the cycle
# loop costs exactly one is-None check in that state.
# --------------------------------------------------------------------------


def enabled() -> bool:
    return env_schema.get_bool(env_schema.HOROVOD_MEGAPLAN)


def get_manager() -> Optional[MegaplanManager]:
    return _MANAGER


def init_manager(rank: int = 0) -> Optional[MegaplanManager]:
    """Create the process manager when ``HOROVOD_MEGAPLAN`` is set
    (idempotent); no-op returning None when off."""
    global _MANAGER
    if not enabled():
        return _MANAGER
    if _MANAGER is None:
        _MANAGER = MegaplanManager(rank=rank)
    return _MANAGER


def reset_manager() -> None:
    """Drop the process manager (test/bench helper)."""
    global _MANAGER
    _MANAGER = None


def report() -> dict:
    """``hvd.megaplan_report()`` body: ``{"enabled": False}`` when off,
    else capture/replay counters, hit rate and the live plan's shape."""
    mgr = _MANAGER
    if mgr is None:
        return {"enabled": False}
    return mgr.report()
