"""Pallas TPU kernels (SURVEY.md §5.7): fused block attention for the
sequence-parallel path."""

from .flash_attention import (  # noqa: F401
    attention_stats,
    flash_attention,
    flash_attention_stats,
)
