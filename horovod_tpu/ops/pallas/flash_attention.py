"""Fused block (flash) attention — the Pallas TPU kernel behind
`horovod_tpu.parallel.sp.ring_attention`'s inner step (SURVEY.md §5.7
"pallas splash-attention kernels"; greenfield — the reference has no
attention kernels at all).

Forward is a single Pallas kernel: for each Q block the K/V blocks stream
through VMEM while an online softmax (running max ``m``, running sum ``l``,
rescaled accumulator) lives in VMEM scratch — logits never round-trip to
HBM, which is the whole point on a bandwidth-bound chip. The kernel also
returns ``(m, l)`` so ring attention can combine partial results from
other chips' K/V shards exactly.

Backward is a rematerialized BLOCKWISE VJP: autodiff through
``scan_stats`` — a ``lax.scan`` over K/V blocks with a checkpointed
body — so both directions hold one [B, sq, block_k] score block, never
the full matrix. Only q/k/v are residuals. A fused backward kernel is
a later optimization.

On non-TPU backends the kernel runs in Pallas interpret mode (tests on the
virtual CPU mesh), so one code path serves everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                      acc_scr, m_scr, l_scr, *, scale: float, causal: bool,
                      causal_offset: int, block_q: int, block_k: int,
                      num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _block():
        q = q_ref[0]                      # [bq, d]
        k = k_ref[0]                      # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            # causal_offset=0: standard (row >= col); =1: STRICT (row > col)
            # — striped ring attention's j>i rounds exclude the diagonal
            rows = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols + causal_offset, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + p.sum(axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    if causal:
        # skip blocks whose mask is entirely empty
        @pl.when(ki * block_k + causal_offset < (qi + 1) * block_q)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        # guard fully-masked rows (l == 0 never happens when causal includes
        # the diagonal, but ring callers may pass degenerate blocks)
        l = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "causal_offset"))
def _flash_fwd(q, k, v, causal: bool, block_q: int, block_k: int,
               causal_offset: int = 0):
    """q: [B, sq, d], k/v: [B, sk, d] → (o [B, sq, d], m [B, sq], l [B, sq]).

    o is *normalized* (already divided by l); combining across ring steps
    uses (m, l) to undo/redo normalization exactly.
    """
    B, sq, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq or sk % bk:
        raise ValueError(
            f"sequence lengths ({sq}, {sk}) must be divisible by the block "
            f"sizes ({bq}, {bk}); pick block_q/block_k that tile the "
            "sequence or use the blockwise XLA fallback (scan_stats / "
            "use_flash=False)")
    nq, nk = sq // bq, sk // bk
    scale = d ** -0.5

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        causal_offset=causal_offset, block_q=bq, block_k=bk,
        num_k_blocks=nk)
    from jax.experimental.pallas import tpu as pltpu

    o, m, l = pl.pallas_call(
        kernel,
        grid=(B, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, sq, d), q.dtype),
            jax.ShapeDtypeStruct((B, sq), jnp.float32),
            jax.ShapeDtypeStruct((B, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, m, l


def _reference_attention(q, k, v, causal: bool, causal_offset: int = 0):
    """Plain XLA attention used by the backward rematerialization and as
    the numerics oracle in tests. q/k/v: [B, s, d]."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool),
                        k=-causal_offset)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512):
    """Fused attention: q [B, sq, d] × k/v [B, sk, d] → [B, sq, d]."""
    o, _, _ = _flash_fwd(q, k, v, causal, block_q, block_k)
    return o


def flash_attention_stats(q, k, v, causal: bool = True, block_q: int = 512,
                          block_k: int = 512):
    """Forward returning (o, m, l) for cross-chip (ring) combination."""
    return _flash_fwd(q, k, v, causal, block_q, block_k)


def _lax_stats(q, k, v, causal: bool, causal_offset: int = 0):
    """Pure-XLA stats attention: (normalized o, running max m, sum l) in the
    same contract as the Pallas kernel. Serves as the differentiable
    fallback (non-TPU backends) and the autodiff oracle for the kernel's
    rematerialized VJP."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool),
                        k=-causal_offset)
        s = jnp.where(mask, s, NEG_INF)
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    o = jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v).astype(jnp.float32)
    o = (o / jnp.where(l == 0.0, 1.0, l)[..., None]).astype(q.dtype)
    return o, m, l


def scan_stats(q, k, v, causal: bool = True, causal_offset: int = 0,
               block_k: int = 512):
    """Blockwise stats attention: same (normalized o, m, l) contract as
    the Pallas kernel and ``_lax_stats``, computed as a ``lax.scan`` over
    K/V blocks with a rematerialized body — so BOTH autodiff directions
    hold only one [B, sq, block_k] score block, never the full
    [B, sq, sk] matrix. This is the memory-honest backward for the
    flash forward (the dense VJP it replaces materialized the full
    score matrix, defeating the kernel's point for long shards)."""
    B, sq, d = q.shape
    sk = k.shape[1]
    bk = min(block_k, sk)
    if sk % bk:
        # largest divisor of sk that is <= block_k: stays blockwise for
        # any length without degenerating to tiny blocks (a decrement
        # loop could land on bk=1 for near-prime lengths)
        bk = max(d_ for d_ in range(1, bk + 1) if sk % d_ == 0)
    n = sk // bk
    scale = d ** -0.5
    qf = q.astype(jnp.float32)
    kb = k.reshape(B, n, bk, d).swapaxes(0, 1)
    vb = v.reshape(B, n, bk, d).swapaxes(0, 1)
    rows = lax.broadcasted_iota(jnp.int32, (sq, bk), 0)
    cols0 = lax.broadcasted_iota(jnp.int32, (sq, bk), 1)

    def body(carry, inp):
        m, l, acc = carry
        kj, vj, j = inp
        sblk = jnp.einsum("bqd,bkd->bqk", qf,
                          kj.astype(jnp.float32)) * scale
        if causal:
            mask = rows >= (j * bk + cols0) + causal_offset
            sblk = jnp.where(mask[None], sblk, NEG_INF)
        m_new = jnp.maximum(m, sblk.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sblk - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = (acc * alpha[..., None]
               + jnp.einsum("bqk,bkd->bqd", p, vj.astype(jnp.float32)))
        return (m_new, l, acc), None

    # init derives from the data so its device-varying (vma) type matches
    # the body outputs when traced inside a shard_map (constants are
    # replication-typed and lax.scan demands equal carry types)
    zrow = qf[..., 0] * 0.0                       # [B, sq], varies like q
    init = (zrow + NEG_INF, zrow, qf * 0.0)
    (m, l, acc), _ = lax.scan(jax.checkpoint(body), init,
                              (kb, vb, jnp.arange(n)))
    o = (acc / jnp.where(l == 0.0, 1.0, l)[..., None]).astype(q.dtype)
    return o, m, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def attention_stats(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, causal_offset: int = 0):
    """Differentiable stats attention: Pallas kernel on TPU for the primal,
    rematerialized XLA VJP for the backward (cotangents of o, m, l all
    handled — ring combination makes m and l real outputs, not residuals).
    """
    return _flash_fwd(q, k, v, causal, block_q, block_k, causal_offset)


def _stats_fwd(q, k, v, causal, block_q, block_k, causal_offset):
    out = _flash_fwd(q, k, v, causal, block_q, block_k, causal_offset)
    return out, (q, k, v)


def _stats_bwd(causal, block_q, block_k, causal_offset, res, cts):
    q, k, v = res
    # blockwise recompute: never materializes [B, sq, sk]
    _, vjp = jax.vjp(
        lambda a, b, c: scan_stats(a, b, c, causal, causal_offset, block_k),
        q, k, v)
    return vjp(cts)


attention_stats.defvjp(_stats_fwd, _stats_bwd)


def _fwd(q, k, v, causal, block_q, block_k):
    o, m, l = _flash_fwd(q, k, v, causal, block_q, block_k)
    # only the inputs are residuals: the blockwise VJP recomputes its
    # own stats, so o/lse must not stay live across fwd->bwd
    return o, (q, k, v)


def _bwd(causal, block_q, block_k, res, do):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda a, b, c: scan_stats(a, b, c, causal, 0, block_k)[0], q, k, v)
    return vjp(do)


flash_attention.defvjp(_fwd, _bwd)
