"""Negotiation wire format v2: versioned, length-delimited binary frames.

Reference: /root/reference/horovod/common/wire/message.fbs — the
reference serializes controller messages with FlatBuffers precisely
because the per-round control traffic is hot enough that a text codec
shows up at scale. Our v1 wire is JSON (ops/controller.py module
docstring explains why); at pod scale the per-round JSON bytes and
parse cost grow with world size, so v2 replaces the payloads with a
compact binary encoding while keeping the *protocol* (rounds, scopes,
SAME_AS_LAST marker, traced ``"t"`` suffix) bit-compatible.

Frame grammar (all integers LEB128 varints unless sized):

    frame     := MAGIC_V2 kind body
    kind      := SUBMIT(0x01) | AGG(0x02) | RESP(0x03)

    SUBMIT    := flags [f64 t] n_entries { str(name) sigref(sig) }
                 -- flags: 1 joined, 2 shutting_down, 4 has_t
    AGG       := flags group size bitmap(covered) bitmap(joined)
                 bitmap(sd) n_entries { str(name) sigref(sig)
                 bitmap(ranks) } [tmap]
                 -- flags: 1 has_tmap; tmap := n { rank f64 t }
    RESP      := flags n_ready { str(name) sigref(sig) }
                 n_errors { str(name) str(msg) } [join_done]
                 [n_strag { str(name) rank f64 wait }] [wv]
                 [len json(params)]
                 -- flags: 1 join_done, 2 shutdown_done, 4 invalidate,
                    8 has_params, 16 has_strag, 32 has_wv

Strings are interned: the first occurrence in a frame (SUBMIT/AGG) or on
a channel (RESP) carries the bytes and binds the next id; later
occurrences are a 1-2 byte reference. SUBMIT/AGG frames are
self-contained — a leader fail-over or flat fallback mid-stream must
never leave a decoder holding bindings the encoder has forgotten — while
the RESP channel interns across rounds (single writer, and the lockstep
guarantees every rank decodes every response in order), which is where
the repetition actually lives: ``allreduce``/``float32``/``global``
style signature atoms recur every round under fresh tensor names.

Whole signatures intern the same way (``sigref``): gradients in one
model overwhelmingly share a handful of (shape, dtype, op, scale)
tuples, so the first occurrence carries the tagged value and later
entries — and on the RESP channel, later *rounds* — are a 1-2 byte
reference. Decoders hand back the one decoded object per binding;
callers treat signatures as immutable (the controller only ever
compares and re-serializes them).

The first byte ``MAGIC_V2`` (0x02) collides with neither JSON payloads
(``{``/``[``) nor the 1-byte SAME_AS_LAST marker (``=``, 0x3D), so
decoders sniff the format per value and mixed-version worlds degrade to
v1 without flag-day coordination (docs/scaling.md covers the
handshake).
"""

from __future__ import annotations

import json
import struct
from typing import Optional

MAGIC_V2 = 0x02
WIRE_V1 = 1
WIRE_V2 = 2

KIND_SUBMIT = 0x01
KIND_AGG = 0x02
KIND_RESP = 0x03

# value codec tags (signature lists are heterogenous: strings, ints,
# floats, nested lists, None for absent root ranks)
_T_NULL, _T_FALSE, _T_TRUE, _T_INT, _T_FLOAT, _T_STR, _T_LIST = range(7)


class WireDecodeError(ValueError):
    """A v2 frame failed to parse (truncation, bad tag, dangling intern
    reference). Decoders raise this instead of struct/index errors so
    the controller can attribute the failure to the wire layer."""


# -- varints ---------------------------------------------------------------

def _enc_uvarint(out: bytearray, v: int) -> None:
    while v > 0x7F:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _enc_svarint(out: bytearray, v: int) -> None:
    _enc_uvarint(out, (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def u8(self) -> int:
        try:
            b = self.buf[self.pos]
        except IndexError:
            raise WireDecodeError("truncated frame") from None
        self.pos += 1
        return b

    def uvarint(self) -> int:
        shift = v = 0
        while True:
            b = self.u8()
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 63:
                raise WireDecodeError("varint overflow")

    def svarint(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)

    def f64(self) -> float:
        end = self.pos + 8
        if end > len(self.buf):
            raise WireDecodeError("truncated f64")
        (v,) = struct.unpack_from("<d", self.buf, self.pos)
        self.pos = end
        return v

    def raw(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.buf):
            raise WireDecodeError("truncated bytes")
        v = self.buf[self.pos:end]
        self.pos = end
        return v


# -- string interning ------------------------------------------------------

class Interner:
    """Encoder half of the string table: first sight writes the bytes
    and binds the next id, repeats write a reference (id<<1|0 vs the
    new-binding marker id<<1|1 — one bit, not a separate tag byte)."""

    __slots__ = ("_ids",)

    def __init__(self):
        self._ids: dict[str, int] = {}

    def encode(self, out: bytearray, s: str) -> None:
        i = self._ids.get(s)
        if i is not None:
            _enc_uvarint(out, i << 1)
            return
        self._ids[s] = len(self._ids)
        raw = s.encode("utf-8")
        _enc_uvarint(out, (len(self._ids) - 1) << 1 | 1)
        _enc_uvarint(out, len(raw))
        out += raw


class StringTable:
    """Decoder half: ids resolve in binding order. Monotone — nothing
    ever unbinds, so a decoder that has seen every prior frame on the
    channel (the lockstep guarantee) can never dangle."""

    __slots__ = ("_strs",)

    def __init__(self):
        self._strs: list[str] = []

    def decode(self, r: _Reader) -> str:
        ref = r.uvarint()
        if ref & 1:
            n = r.uvarint()
            try:
                s = r.raw(n).decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireDecodeError(f"bad utf-8 in interned string: {e}")
            if ref >> 1 != len(self._strs):
                raise WireDecodeError("out-of-order intern binding")
            self._strs.append(s)
            return s
        i = ref >> 1
        if i >= len(self._strs):
            raise WireDecodeError(f"dangling intern reference {i}")
        return self._strs[i]


# -- tagged values (signatures) -------------------------------------------

def _enc_value(out: bytearray, v, intern: Interner) -> None:
    if v is None:
        out.append(_T_NULL)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        _enc_svarint(out, v)
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out += struct.pack("<d", v)
    elif isinstance(v, str):
        out.append(_T_STR)
        intern.encode(out, v)
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _enc_uvarint(out, len(v))
        for item in v:
            _enc_value(out, item, intern)
    else:
        raise TypeError(f"unencodable signature element: {type(v)!r}")


def _dec_value(r: _Reader, table: StringTable):
    tag = r.u8()
    if tag == _T_NULL:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return r.svarint()
    if tag == _T_FLOAT:
        return r.f64()
    if tag == _T_STR:
        return table.decode(r)
    if tag == _T_LIST:
        return [_dec_value(r, table) for _ in range(r.uvarint())]
    raise WireDecodeError(f"unknown value tag {tag}")


# -- signature interning ---------------------------------------------------

class _SigEncoder:
    """Whole-signature interning over a value codec: repeats of an
    identical signature write a 1-2 byte reference instead of the full
    tagged value (same id<<1|new-bit scheme as :class:`Interner`).
    Keyed by the canonical JSON of the signature — deterministic for
    equal inputs, so SAME_AS_LAST byte comparison still holds."""

    __slots__ = ("_intern", "_ids")

    def __init__(self, intern: Interner):
        self._intern = intern
        self._ids: dict[str, int] = {}

    def encode(self, out: bytearray, sig) -> None:
        key = json.dumps(sig)
        i = self._ids.get(key)
        if i is not None:
            _enc_uvarint(out, i << 1)
            return
        self._ids[key] = len(self._ids)
        _enc_uvarint(out, (len(self._ids) - 1) << 1 | 1)
        _enc_value(out, sig, self._intern)


class _SigDecoder:
    """Decoder half: bindings resolve in order, the decoded object is
    shared between references (callers never mutate signatures)."""

    __slots__ = ("_table", "_sigs")

    def __init__(self, table: StringTable):
        self._table = table
        self._sigs: list = []

    def decode(self, r: _Reader):
        ref = r.uvarint()
        if ref & 1:
            if ref >> 1 != len(self._sigs):
                raise WireDecodeError("out-of-order sig binding")
            v = _dec_value(r, self._table)
            self._sigs.append(v)
            return v
        i = ref >> 1
        if i >= len(self._sigs):
            raise WireDecodeError(f"dangling sig reference {i}")
        return self._sigs[i]


# -- rank bitmaps ----------------------------------------------------------

def _enc_bitmap(out: bytearray, ranks, size: int) -> None:
    bits = bytearray((size + 7) // 8)
    for k in ranks:
        if not 0 <= k < size:
            raise ValueError(f"rank {k} outside world of {size}")
        bits[k >> 3] |= 1 << (k & 7)
    out += bits


def _dec_bitmap(r: _Reader, size: int) -> set:
    raw = r.raw((size + 7) // 8)
    out = set()
    for byte_i, b in enumerate(raw):
        while b:
            low = b & -b
            out.add((byte_i << 3) + low.bit_length() - 1)
            b ^= low
    return out


# -- SUBMIT frames ---------------------------------------------------------

def encode_submission(entries, joined: bool, shutting_down: bool,
                      t: Optional[float] = None) -> bytes:
    """One worker's (or group member's) round submission.

    ``entries`` is the negotiate() pending view: an iterable of
    ``(name, sig)``. ``t`` is the traced clock-aligned submit time —
    deliberately OUTSIDE the SAME_AS_LAST comparison, so callers encode
    the comparable payload with ``t=None`` and re-encode with the
    timestamp only for the wire (mirrors the v1 JSON split)."""
    out = bytearray((MAGIC_V2, KIND_SUBMIT))
    flags = (1 if joined else 0) | (2 if shutting_down else 0)
    if t is not None:
        flags |= 4
    out.append(flags)
    if t is not None:
        out += struct.pack("<d", t)
    items = list(entries)
    _enc_uvarint(out, len(items))
    intern = Interner()
    sig_enc = _SigEncoder(intern)
    for name, sig in items:
        intern.encode(out, name)
        sig_enc.encode(out, sig)
    return bytes(out)


def decode_submission(raw: bytes) -> dict:
    """Returns the v1-shaped message dict ``{"e": [[name, sig], ...],
    "j": bool, "sd": bool}`` plus ``"t"`` when the frame carries a
    traced submit time — drop-in for ``json.loads`` of a v1 payload."""
    r = _Reader(raw)
    if r.u8() != MAGIC_V2 or r.u8() != KIND_SUBMIT:
        raise WireDecodeError("not a v2 SUBMIT frame")
    flags = r.u8()
    msg: dict = {"j": bool(flags & 1), "sd": bool(flags & 2)}
    if flags & 4:
        msg["t"] = r.f64()
    table = StringTable()
    sig_dec = _SigDecoder(table)
    msg["e"] = [[table.decode(r), sig_dec.decode(r)]
                for _ in range(r.uvarint())]
    return msg


# -- AGG frames (leader -> coordinator) ------------------------------------

def encode_aggregate(group: int, size: int, entries, covered, joined,
                     shutting_down, t_map: Optional[dict] = None) -> bytes:
    """A node leader's merged round: ``entries`` is ``[(name, sig,
    ranks)]`` (duplicate names with different sigs are legal — the
    coordinator's mismatch validation wants to see both sides),
    ``covered`` the ranks this aggregate answers for, ``joined``/
    ``shutting_down`` the subsets that set those flags, ``t_map`` the
    traced per-rank submit times. Like SUBMIT, callers build the
    SAME_AS_LAST-comparable encoding with ``t_map=None``."""
    out = bytearray((MAGIC_V2, KIND_AGG))
    out.append(1 if t_map else 0)
    _enc_uvarint(out, group)
    _enc_uvarint(out, size)
    _enc_bitmap(out, covered, size)
    _enc_bitmap(out, joined, size)
    _enc_bitmap(out, shutting_down, size)
    items = list(entries)
    _enc_uvarint(out, len(items))
    intern = Interner()
    sig_enc = _SigEncoder(intern)
    for name, sig, ranks in items:
        intern.encode(out, name)
        sig_enc.encode(out, sig)
        _enc_bitmap(out, ranks, size)
    if t_map:
        _enc_uvarint(out, len(t_map))
        for k in sorted(t_map):
            _enc_uvarint(out, k)
            out += struct.pack("<d", float(t_map[k]))
    return bytes(out)


def decode_aggregate(raw: bytes) -> dict:
    """Returns ``{"g": group, "e": [[name, sig, set(ranks)], ...],
    "covered": set, "j": set, "sd": set}`` plus ``"t"`` (rank -> time)
    when traced."""
    r = _Reader(raw)
    if r.u8() != MAGIC_V2 or r.u8() != KIND_AGG:
        raise WireDecodeError("not a v2 AGG frame")
    flags = r.u8()
    group = r.uvarint()
    size = r.uvarint()
    msg: dict = {"g": group,
                 "covered": _dec_bitmap(r, size),
                 "j": _dec_bitmap(r, size),
                 "sd": _dec_bitmap(r, size)}
    table = StringTable()
    sig_dec = _SigDecoder(table)
    msg["e"] = [[table.decode(r), sig_dec.decode(r),
                 _dec_bitmap(r, size)]
                for _ in range(r.uvarint())]
    if flags & 1:
        msg["t"] = {r.uvarint(): r.f64() for _ in range(r.uvarint())}
    return msg


def is_aggregate(raw: bytes) -> bool:
    return len(raw) >= 2 and raw[0] == MAGIC_V2 and raw[1] == KIND_AGG


# -- RESP frames (coordinator -> everyone) ---------------------------------

_F_JOIN_DONE = 1
_F_SHUTDOWN = 2
_F_INVALIDATE = 4
_F_PARAMS = 8
_F_STRAG = 16
_F_WV = 32


class ResponseEncoder:
    """Coordinator-held encoder for the response channel. Interns
    strings ACROSS rounds — safe because the coordinator is the only
    writer and the lockstep makes every rank decode every response in
    publication order (a rank that misses one is broken and
    re-initializes with a fresh table)."""

    def __init__(self):
        self._intern = Interner()
        self._sig_enc = _SigEncoder(self._intern)

    def encode(self, resp: dict) -> bytes:
        out = bytearray((MAGIC_V2, KIND_RESP))
        flags = 0
        if resp.get("join_done") is not None:
            flags |= _F_JOIN_DONE
        if resp.get("shutdown_done"):
            flags |= _F_SHUTDOWN
        if resp.get("invalidate"):
            flags |= _F_INVALIDATE
        if resp.get("params") is not None:
            flags |= _F_PARAMS
        if resp.get("strag"):
            flags |= _F_STRAG
        if resp.get("wv") is not None:
            flags |= _F_WV
        out.append(flags)
        ready = resp.get("ready", [])
        sigs = resp.get("sigs", {})
        _enc_uvarint(out, len(ready))
        for name in ready:
            self._intern.encode(out, name)
            self._sig_enc.encode(out, sigs[name])
        errors = resp.get("errors", {})
        _enc_uvarint(out, len(errors))
        for name, emsg in errors.items():
            self._intern.encode(out, name)
            self._intern.encode(out, emsg)
        if flags & _F_JOIN_DONE:
            _enc_uvarint(out, int(resp["join_done"]))
        if flags & _F_STRAG:
            strag = resp["strag"]
            _enc_uvarint(out, len(strag))
            for name, (last, wait) in strag.items():
                self._intern.encode(out, name)
                _enc_uvarint(out, int(last))
                out += struct.pack("<d", float(wait))
        if flags & _F_WV:
            _enc_uvarint(out, int(resp["wv"]))
        if flags & _F_PARAMS:
            blob = json.dumps(resp["params"]).encode()
            _enc_uvarint(out, len(blob))
            out += blob
        return bytes(out)


class ResponseDecoder:
    """Worker-held decoder for the response channel (one per
    controller, tables advance with the lockstep). Returns the same
    dict shape ``json.loads`` yields for a v1 response."""

    def __init__(self):
        self._table = StringTable()
        self._sig_dec = _SigDecoder(self._table)

    def decode(self, raw: bytes) -> dict:
        r = _Reader(raw)
        if r.u8() != MAGIC_V2 or r.u8() != KIND_RESP:
            raise WireDecodeError("not a v2 RESP frame")
        flags = r.u8()
        ready = []
        sigs = {}
        for _ in range(r.uvarint()):
            name = self._table.decode(r)
            ready.append(name)
            sigs[name] = self._sig_dec.decode(r)
        errors = {}
        for _ in range(r.uvarint()):
            name = self._table.decode(r)
            errors[name] = self._table.decode(r)
        resp: dict = {"ready": ready, "sigs": sigs, "errors": errors,
                      "join_done": None}
        if flags & _F_JOIN_DONE:
            resp["join_done"] = r.uvarint()
        if flags & _F_STRAG:
            resp["strag"] = {
                self._table.decode(r): [r.uvarint(), r.f64()]
                for _ in range(r.uvarint())}
        if flags & _F_WV:
            resp["wv"] = r.uvarint()
        if flags & _F_PARAMS:
            try:
                resp["params"] = json.loads(r.raw(r.uvarint()))
            except ValueError as e:
                raise WireDecodeError(f"bad params blob: {e}")
        if flags & _F_SHUTDOWN:
            resp["shutdown_done"] = True
        if flags & _F_INVALIDATE:
            resp["invalidate"] = True
        return resp
